"""Executor: compiled forward/backward of a bound symbolic graph.

TPU-native equivalent of the reference GraphExecutor (reference:
src/executor/graph_executor.cc, python/mxnet/executor.py). Where the
reference builds per-node engine ops with a shared memory pool
(InitCachedOps :1174, MXPlanMemory), here bind lowers the whole graph to
one jitted XLA computation; backward is the jit-compiled vjp. Loss-head
semantics of the legacy output ops are honored: softmax_output's backward
is (softmax - one_hot(label)), make_loss's head gradient is 1 — matching
FGradient of the reference ops (src/operator/softmax_output.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from .utils import compile_cache as _cc

__all__ = ["Executor"]

_LOSS_HEADS = ("softmax_output", "make_loss", "linear_regression_output",
               "logistic_regression_output", "mae_regression_output")


class Executor:
    def __init__(self, symbol, arg_names, arg_arrays, grad_arrays, grad_req,
                 ctx=None, aux_names=(), aux_arrays=(), output_shapes=None):
        self._symbol = symbol
        self.arg_names = list(arg_names)
        self.arg_arrays = list(arg_arrays)
        self.grad_arrays = grad_arrays
        self.grad_req = grad_req
        # auxiliary states (BN running stats): fed to the graph, never
        # differentiated (reference: executor.h aux_states)
        self.aux_names = list(aux_names)
        self.aux_arrays = list(aux_arrays)
        # bind-time inferred output shapes (reference GraphExecutor keeps
        # them from bind) — lets predictors size buffers before forward
        # without re-running whole-graph inference
        self.output_shapes = (None if output_shapes is None
                              else [tuple(s) for s in output_shapes])
        self.outputs = []
        self._ctx = ctx
        self._fwd_jit = None
        self._label_names = [n for n in self.arg_names
                             if n.endswith("label")]
        self._analyze_on_bind()

    def _analyze_on_bind(self):
        """Bind-time static analysis: MXNET_GRAPH_VERIFY-gated
        verification (the analog of the reference's bind-time attribute
        passes, infer_graph_attr_pass.cc, run as diagnostics instead of
        CHECKs) followed by the MXNET_GRAPH_OPT-gated rewrite pipeline.
        Both phases share ONE ``PassContext`` fact cache, so
        verify-then-optimize runs shape/dtype inference once. The
        rewrite replaces ``self._symbol``; the optimizer re-verifies its
        own output and falls back to the original on any new error.
        Feeds are name-keyed, so the bound arg/aux lists stay valid for
        any rewrite (rewrites never drop referenced variables)."""
        from . import analysis
        from .analysis import graph_opt

        mode = analysis.verify_mode()
        level = graph_opt.opt_level()
        if mode == "off" and level == 0:
            return
        shapes, dtypes = {}, {}
        for n, a in zip(self.arg_names + self.aux_names,
                        self.arg_arrays + self.aux_arrays):
            if a is not None:
                shapes[n] = tuple(a.shape)
                dtypes[n] = a.dtype
        subject = f"bind:{self._symbol._name or 'symbol'}"
        ctx = analysis.PassContext(self._symbol, shapes=shapes,
                                   dtypes=dtypes, subject=subject)
        if mode != "off":
            analysis.run_passes(ctx)
            ctx.report.disposition()
        if level > 0:
            self._symbol, _ = graph_opt.optimize_symbol(
                self._symbol, shapes=shapes, dtypes=dtypes, level=level,
                ctx=ctx, subject=subject)

    @property
    def arg_dict(self):
        return dict(zip(self.arg_names, self.arg_arrays))

    @property
    def grad_dict(self):
        if self.grad_arrays is None:
            return {}
        return {n: g for n, g in zip(self.arg_names, self.grad_arrays)
                if g is not None}

    @property
    def aux_dict(self):
        return dict(zip(self.aux_names, self.aux_arrays))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """Reference: executor.py copy_params_from."""
        def _check(name, src, dst):
            # copyto replaces the payload wholesale, so a mismatched
            # checkpoint must fail HERE with a clear error, not later as
            # an opaque jit trace error at first forward
            if tuple(src.shape) != tuple(dst.shape):
                raise ValueError(
                    f"param '{name}' has shape {tuple(src.shape)} but the "
                    f"executor binds it as {tuple(dst.shape)}")

        for name, array in arg_params.items():
            if name in self.arg_dict:
                _check(name, array, self.arg_dict[name])
                array.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise ValueError(f"Found name '{name}' that is not in the "
                                 "arguments")
        for name, array in (aux_params or {}).items():
            if name in self.aux_dict:
                _check(name, array, self.aux_dict[name])
                array.copyto(self.aux_dict[name])
            elif not allow_extra_params:
                raise ValueError(f"Found name '{name}' that is not in the "
                                 "aux states")

    # ---- compiled paths --------------------------------------------------
    def _ensure_fwd(self):
        if self._fwd_jit is not None:
            return
        symbol = self._symbol
        names = self.arg_names + self.aux_names
        aux_index = {n: i for i, n in enumerate(self.aux_names)}
        # BatchNorm nodes whose running stats live in our aux arrays:
        # training forward must fold fresh batch statistics into them
        # (reference: BN FMutateInputs mutates aux in Forward)
        bn_specs = []
        for node in symbol._walk():
            if node._op == "batch_norm" and len(node._inputs) >= 5:
                if node._kwargs.get("use_global_stats"):
                    continue  # frozen BN: never update running stats
                mname = node._inputs[3]._name
                vname = node._inputs[4]._name
                if mname in aux_index and vname in aux_index:
                    bn_specs.append(
                        (node, aux_index[mname], aux_index[vname],
                         float(node._kwargs.get("momentum", 0.9)),
                         int(node._kwargs.get("axis", 1))))

        def fwd(vals, train):
            from . import autograd

            with autograd.pause(train_mode=train):
                feed = {n: NDArray(v) for n, v in zip(names, vals)}
                cache = {}
                out = symbol._eval_nodes(feed, cache)
                if isinstance(out, (list, tuple)) and \
                        symbol._num_outputs > 1:
                    out = out[symbol._output_index]
                aux_new = ()
                if train and bn_specs:
                    upd = list(vals[len(self.arg_names):])
                    for node, mi, vi, mom, bax in bn_specs:
                        xv = node._inputs[0]._eval_nodes(feed, cache)
                        if isinstance(xv, (list, tuple)):
                            xv = xv[node._inputs[0]._output_index]
                        xd = xv.data.astype(jnp.float32)
                        ax = tuple(i for i in range(xd.ndim)
                                   if i != bax % xd.ndim)
                        bm = jnp.mean(xd, axis=ax)
                        bv = jnp.var(xd, axis=ax)
                        upd[mi] = mom * upd[mi] + (1 - mom) * bm
                        upd[vi] = mom * upd[vi] + (1 - mom) * bv
                    aux_new = tuple(upd)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return tuple(o.data for o in outs), aux_new

        self._fwd_full_jit = _cc.counting_jit(fwd, label="executor_fwd_full",
                                              static_argnums=(1,))

        def fwd_only(vals, train):
            return fwd(vals, train)[0]

        self._fwd_jit = _cc.counting_jit(fwd_only, label="executor_fwd",
                                         static_argnums=(1,))

        # loss-aware scalar function for backward
        def loss_fn(vals):
            from . import autograd

            with autograd.pause(train_mode=True):
                feed = {n: NDArray(v) for n, v in zip(names, vals)}
                total = 0.0
                head_syms = (symbol._group if symbol._group else [symbol])
                cache = {}
                for h in head_syms:
                    if h._op == "softmax_output":
                        data = h._inputs[0]._eval_nodes(feed, cache)
                        label = h._inputs[1]._eval_nodes(feed, cache)
                        logp = jax.nn.log_softmax(data.data, axis=-1)
                        onehot = jax.nn.one_hot(label.data.astype(jnp.int32),
                                                data.shape[-1])
                        # normalization='null' (reference default):
                        # head grad is (softmax - onehot), unscaled
                        total = total - jnp.sum(logp * onehot)
                    elif h._op == "linear_regression_output":
                        data = h._inputs[0]._eval_nodes(feed, cache)
                        label = h._inputs[1]._eval_nodes(feed, cache)
                        total = total + 0.5 * jnp.sum(
                            jnp.square(data.data - label.data.reshape(
                                data.shape)))
                    elif h._op == "logistic_regression_output":
                        data = h._inputs[0]._eval_nodes(feed, cache)
                        label = h._inputs[1]._eval_nodes(feed, cache)
                        p = jax.nn.sigmoid(data.data)
                        lbl = label.data.reshape(data.shape)
                        total = total - jnp.sum(
                            lbl * jnp.log(p + 1e-12)
                            + (1 - lbl) * jnp.log(1 - p + 1e-12))
                    elif h._op == "mae_regression_output":
                        data = h._inputs[0]._eval_nodes(feed, cache)
                        label = h._inputs[1]._eval_nodes(feed, cache)
                        total = total + jnp.sum(jnp.abs(
                            data.data - label.data.reshape(data.shape)))
                    else:  # make_loss or generic head: sum it
                        out = h._eval_nodes(feed, cache)
                        outs = out if isinstance(out, (list, tuple)) else [out]
                        total = total + sum(jnp.sum(o.data) for o in outs)
                return total

        from . import env

        if env.get_bool("MXNET_BACKWARD_DO_MIRROR"):
            # reference mirror pass (src/nnvm/gradient.cc:275) — remat:
            # backward recomputes activations instead of keeping them
            loss_fn = jax.checkpoint(loss_fn)
            fwd_for_vjp = jax.checkpoint(lambda v: fwd_only(v, True))
        else:
            fwd_for_vjp = lambda v: fwd_only(v, True)  # noqa: E731
        # data parallelism over a device mesh (reference:
        # DataParallelExecutorGroup batch split, executor_group.py:282 —
        # here ONE computation with batch inputs sharded over 'dp';
        # GSPMD inserts the gradient all-reduces the reference ran
        # through kvstore device comm)
        self._grad_jit = _cc.counting_jit(jax.grad(loss_fn),
                                          label="executor_grad")

        def head_vjp(vals, cots):
            _, vjp_fn = jax.vjp(fwd_for_vjp, vals)
            return vjp_fn(cots)[0]

        self._head_vjp_jit = _cc.counting_jit(head_vjp,
                                              label="executor_head_vjp")

    # ---- data parallelism over a mesh -----------------------------------
    def _mesh(self):
        """A 1-axis 'dp' mesh when bound to MULTIPLE contexts
        (reference: Module(context=[...]) → executor group)."""
        ctxs = self._ctx if isinstance(self._ctx, (list, tuple)) else None
        if not ctxs or len(ctxs) < 2:
            return None
        from jax.sharding import Mesh

        import numpy as onp

        return Mesh(onp.array([c.jax_device for c in ctxs]), ("dp",))

    def set_batch_names(self, names):
        """Arguments sharded on the batch axis under a multi-context
        bind (data + labels); everything else replicates. The sharding
        list is built ONCE here — it is invariant per bind, and the
        training hot loop places vals with it every step."""
        self._batch_names = set(names)
        self._shard_cache = self._build_val_shardings()
        if self._shard_cache is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # outputs (and therefore head cotangents) are batch-sharded
            self._batch_shard = NamedSharding(self._mesh(), P("dp"))  # graft-lint: allow(L701)

    def _place_vals(self, vals, shard):
        """Commit vals to the dp-mesh layout (batch args split over
        'dp', the rest replicated); jit then compiles the sharded
        computation and GSPMD inserts the collectives. Identity on a
        single-context bind. The placement is memoized on the val
        identities: a forward→backward pair places ONCE instead of
        broadcasting every replicated param twice per step."""
        if shard is None:
            return vals
        cache = getattr(self, "_place_cache", None)
        if cache is not None and len(cache[0]) == len(vals) and \
                all(a is b for a, b in zip(cache[0], vals)):
            return cache[1]
        placed = [jax.device_put(v, s) for v, s in zip(vals, shard)]
        self._place_cache = (list(vals), placed)
        return placed

    def _val_shardings(self):
        return getattr(self, "_shard_cache", None)

    def _build_val_shardings(self):
        mesh = self._mesh()
        if mesh is None or not getattr(self, "_batch_names", None):
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        batch = NamedSharding(mesh, P("dp"))  # graft-lint: allow(L701)
        rep = NamedSharding(mesh, P())  # graft-lint: allow(L701)
        return [batch if n in self._batch_names else rep
                for n in self.arg_names + self.aux_names]

    # ---- monitor taps ----------------------------------------------------
    def set_monitor_callback(self, callback, monitor_all=False):
        """Tap every op output by name during forward (reference:
        executor.py set_monitor_callback → MXExecutorSetMonitorCallback,
        graph_executor.cc:1343-1382). With ``monitor_all``, bound input
        variables are reported too. The taps are ONE extra jitted
        computation returning the cached node outputs — fusion of the
        main forward is untouched."""
        self._mon_cb = callback
        self._mon_all = bool(monitor_all)
        self._mon_jit = None

    def _ensure_monitor(self):
        if getattr(self, "_mon_jit", None) is not None:
            return
        symbol = self._symbol
        names_in = self.arg_names + self.aux_names
        # dedup multi-output views on the same identity key _eval_nodes
        # caches on, preserving topological order
        taps, seen = [], set()
        for node in symbol._walk():
            if node._op is None:
                continue
            key = (node._op, id(node._inputs), id(node._kwargs))
            if key in seen:
                continue
            seen.add(key)
            taps.append(node)
        mon_names = []
        if getattr(self, "_mon_all", False):
            mon_names.extend(names_in)
        for node in taps:
            n_out = getattr(node, "_num_outputs", 1) or 1
            if n_out > 1:  # match Symbol.list_outputs: _output0.._outputN
                mon_names.extend(f"{node._name}_output{i}"
                                 for i in range(n_out))
            else:
                mon_names.append(f"{node._name}_output")
        self._mon_names = mon_names

        def mon_fwd(vals, train):
            from . import autograd

            with autograd.pause(train_mode=train):
                feed = {n: NDArray(v) for n, v in zip(names_in, vals)}
                cache = {}
                outs = []
                if getattr(self, "_mon_all", False):
                    outs.extend(vals)
                for node in taps:
                    out = node._eval_nodes(feed, cache)
                    key = (node._op, id(node._inputs), id(node._kwargs))
                    out = cache.get(key, out)
                    seq = out if isinstance(out, (list, tuple)) else [out]
                    outs.extend(o.data for o in seq)
            return tuple(outs)

        self._mon_jit = _cc.counting_jit(mon_fwd, label="executor_monitor",
                                         static_argnums=(1,))

    def _run_monitor(self, vals, is_train):
        cb = getattr(self, "_mon_cb", None)
        if cb is None:
            return
        # a Monitor only collects between tic/toc every `interval` steps;
        # skip the tap computation entirely on inactive steps
        active = getattr(cb, "mx_monitor_active", None)
        if active is not None and not active():
            return
        self._ensure_monitor()
        tapped = self._mon_jit(vals, bool(is_train))
        for name, val in zip(self._mon_names, tapped):
            cb(name, NDArray(val))

    def forward(self, is_train=False, **kwargs):
        """Reference: executor.py forward / GraphExecutor::RunOps."""
        self._ensure_fwd()
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(
                    f"unknown input '{k}' fed to executor; bound arguments "
                    f"are {self.arg_names}")
            self.arg_dict[k]._data = v.data if isinstance(v, NDArray) \
                else jnp.asarray(v)
        vals = self._place_vals(
            [a.data for a in self.arg_arrays + self.aux_arrays],
            self._val_shardings())
        if is_train and self.aux_arrays:
            outs, aux_new = self._fwd_full_jit(vals, True)
            for arr, new in zip(self.aux_arrays, aux_new):
                arr._data = new
        else:
            outs = self._fwd_jit(vals, bool(is_train))
        self.outputs = [NDArray(o) for o in outs]
        self._run_monitor(vals, is_train)
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        """Reference: executor.py backward / GraphExecutor::Backward.

        With out_grads: vjp of the bound outputs against the supplied head
        gradients. Without: the loss-head rule (softmax_output et al.)."""
        if self.grad_arrays is None or self.grad_req == "null":
            return
        self._ensure_fwd()
        shard = self._val_shardings()
        vals = self._place_vals(
            [a.data for a in self.arg_arrays + self.aux_arrays], shard)
        if out_grads is not None:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cots = tuple(g.data if isinstance(g, NDArray) else jnp.asarray(g)
                         for g in out_grads)
            if shard is not None:
                # head cotangents are batch-shaped: commit them to the
                # mesh like the outputs, or the jit sees mesh vals +
                # single-device cots and rejects the mix
                cots = tuple(jax.device_put(c, self._batch_shard)
                             for c in cots)
            grads = self._head_vjp_jit(vals, cots)
        else:
            grads = self._grad_jit(vals)
        mesh_active = shard is not None
        for name, garr, g in zip(self.arg_names, self.grad_arrays, grads):
            if garr is None:
                continue
            if mesh_active:
                # grads land replicated over the dp mesh; the eager
                # update path (updater/kvstore) runs on the arrays'
                # home device — bring them back (cheap: replicated)
                g = jax.device_put(g, garr.data.sharding)
            if self.grad_req == "add":
                garr._data = garr.data + g
            else:
                garr._data = g

    def warmup(self, is_train=None):
        """Compile the forward (and, when bound for training, backward)
        executables for the CURRENT buffer shapes without touching any
        executor state: outputs are discarded, aux running stats and
        gradient buffers are not written. One device execution on the
        bound buffers is paid per executable — the price of warming
        jit's real call cache (AOT ``lower().compile()`` would compile a
        *separate* executable the later traced calls could not reuse).
        ``BucketingModule.warmup_buckets`` drives this per bucket so all
        buckets compile up front instead of mid-epoch."""
        self._ensure_fwd()
        if is_train is None:
            is_train = self.grad_req != "null" and \
                self.grad_arrays is not None
        vals = self._place_vals(
            [a.data for a in self.arg_arrays + self.aux_arrays],
            self._val_shardings())
        if is_train and self.aux_arrays:
            self._fwd_full_jit(vals, True)
        else:
            self._fwd_jit(vals, bool(is_train))
        if is_train and self.grad_arrays is not None and \
                self.grad_req != "null":
            self._grad_jit(vals)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new shapes (reference: graph_executor.cc:876).
        jit re-specializes per shape automatically; just resize buffers."""
        changed = False
        for name, shape in kwargs.items():
            if name in self.arg_dict:
                i = self.arg_names.index(name)
                self.arg_arrays[i] = nd.zeros(shape)
                if self.grad_arrays is not None and \
                        self.grad_arrays[i] is not None:
                    self.grad_arrays[i] = nd.zeros(shape)
                changed = True
        if changed and self.output_shapes is not None:
            # stale bind-time output shapes would mis-size consumer
            # buffers; re-derive from the resized inputs
            try:
                _, out_shapes, _ = self._symbol.infer_shape(
                    **{n: tuple(a.shape) for n, a in self.arg_dict.items()})
                self.output_shapes = [tuple(s) for s in out_shapes]
            except Exception:
                self.output_shapes = None
        return self
