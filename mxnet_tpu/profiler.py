"""Profiler (reference: python/mxnet/profiler.py over src/profiler/).

Two layers, mirroring the reference design (SURVEY §5.1):
- device/XLA tracing: start/stop drive jax.profiler traces (XPlane /
  TensorBoard format — the TPU-native replacement for the reference's
  chrome://tracing dumps, viewable in Perfetto/TensorBoard);
- host-side scoped stats: Domain/Task/Frame/Event/Counter/Marker objects
  plus an in-process aggregate table (reference aggregate_stats.cc),
  dumped by `dumps()`.
"""
from __future__ import annotations

import json
import threading
import time
from collections import defaultdict

from .utils import locks as _locks

_config = {"filename": "profile.json", "profile_all": False,
           "profile_symbolic": False, "profile_imperative": False,
           "profile_memory": False, "profile_api": False,
           "aggregate_stats": False, "continuous_dump": False}
_state = {"running": False, "jax_trace": False}
# guards: _agg, _events
_lock = _locks.RankedLock("profiler")
_agg = defaultdict(lambda: {"count": 0, "total": 0.0, "min": float("inf"),
                            "max": 0.0})
_events = []  # chrome-trace event dicts


def set_config(**kwargs):
    """Reference: profiler.py:33 set_config."""
    for k, v in kwargs.items():
        if k not in _config:
            raise ValueError(f"unknown profiler option {k}")
        _config[k] = v


def set_state(state="stop", profile_process="worker"):
    if state == "run":
        start()
    else:
        stop()


def start(profile_process="worker"):
    """Start profiling; opens a jax.profiler trace when a filename is
    configured (dir = filename without .json suffix)."""
    if _state["running"]:
        return
    _state["running"] = True
    fname = _config.get("filename")
    if fname:
        try:
            import jax

            logdir = fname[:-5] if fname.endswith(".json") else fname
            jax.profiler.start_trace(logdir + "_xplane")
            _state["jax_trace"] = True
        except Exception:
            _state["jax_trace"] = False


def stop(profile_process="worker"):
    # must finalize the device trace even when pause() flipped `running`
    # off, else the XPlane file is never written and the next start()
    # collides with the still-open trace
    _state["running"] = False
    if _state["jax_trace"]:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # graft-lint: allow(L501)
            pass
        _state["jax_trace"] = False
    if _config.get("continuous_dump"):
        dump()


def pause(profile_process="worker"):
    _state["running"] = False


def resume(profile_process="worker"):
    _state["running"] = True


def is_running():
    return _state["running"]


def imperative_on():
    """Fast gate checked by the op-dispatch layer (reference: the
    PROFILER_MESSAGE taps in src/imperative/imperative_utils.h fire when
    profile_imperative/profile_all is set and the profiler runs)."""
    return _state["running"] and (_config["profile_imperative"]
                                  or _config["profile_all"])


def record_op(name, start_us, dur_us, cached=None):
    """Per-op dispatch timing (NB: JAX dispatch is async — this measures
    host-side dispatch+trace time, not device compute; device timing
    lives in the XPlane trace). ``cached`` marks dispatches served from
    the compiled eager-dispatch cache (registry.py) so a trace shows
    which ops ran as cached executables vs op-by-op."""
    _record("operator", name, start_us, dur_us, cat="imperative",
            cached=cached)


def _family(name):
    """One registry family as a flat dict — the compat-view plumbing.
    Every ``*_counters()`` function below is a thin view over the
    round-18 unified telemetry registry ({} when the owning subsystem
    cannot import)."""
    from .telemetry import metrics as _tm

    _tm._bootstrap_probes()
    return _tm.family_snapshot(name)


def dispatch_cache_counters():
    """Eager-dispatch executable-cache counters (hit/miss/evict/bypass/
    fallback + size), live from the registry. Zeros before first use."""
    return _family("eager_jit_cache")


def fused_step_counters():
    """Fused train-step executable-cache counters (hit/miss/evict/
    bypass/fallback + size) plus the AMP skip-step total, live from
    gluon.fused_step. Zeros before first use. NB: ``skipped_steps``
    reads a device-resident scalar per live trainer, which blocks on
    any in-flight step."""
    return _family("fused_step")


def compile_cache_counters():
    """Persistent compile-cache counters (disk hit/miss/write/corrupt,
    serialize skips, retrace count, bucket pad-ratio), live from
    utils.compile_cache. Zeros before first use."""
    return _family("compile_cache")


def serving_counters():
    """Serving-subsystem counters (requests/responses/failures/
    timeouts/rejected, p50/p95/p99 latency — global and per SLO class
    (``latency_p99_ms:critical`` etc.), queue depth, SLO headroom,
    shed/goodput (``shed_rate``, ``goodput_rps``), canary/model-swap
    transitions, batch-size stats, QPS, warm-start disk hits vs
    compiles, and the round-16 stateful-decode family —
    ``decode_steps`` fused continuous-batching steps, live
    ``slot_occupancy``, ``evictions`` and ``resumed_sessions``), live
    from mxnet_tpu.serving.metrics. Zeros before the first request."""
    return _family("serving")


def pipeline_counters():
    """Async-training-pipeline counters (prefetch depth/hits/stalls,
    stall = engine idle seconds, overlap ratio, dispatch-as-ready grad
    buckets, async kvstore pushes), live from mxnet_tpu.pipeline.
    Zeros before the first DeviceFeed/AsyncGradReducer use."""
    return _family("pipeline")


def resilience_counters():
    """Fault-tolerance counters (checkpoint saves/restores/corrupt
    skips, AutoResume restarts, retry attempts/giveups, circuit-breaker
    trips/demotions, injected-fault fires per point), live from
    mxnet_tpu.resilience. Zeros before first use."""
    return _family("resilience")


def graph_verify_counters():
    """Static graph-verifier counters (graphs checked, diagnostics by
    severity and code), live from mxnet_tpu.analysis. Zeros before the
    first verification (MXNET_GRAPH_VERIFY gated)."""
    return _family("graph_verify")


def graph_opt_counters():
    """Graph-optimizer counters (graphs optimized/rejected, node totals
    before/after, per-pass rewrite counts and time, analysis-run and
    fact-cache tallies), live from mxnet_tpu.analysis.graph_opt. Zeros
    before the first optimization (MXNET_GRAPH_OPT gated)."""
    return _family("graph_opt")


def fusion_counters():
    """Fusion-clustering counters (clusters formed per pattern, nodes
    absorbed, impl selections, fallbacks by reason, serving fused
    pad/slice hits), live from mxnet_tpu.kernels. Zeros before the
    first fused optimization (MXNET_FUSION gated)."""
    return _family("fusion")


def quantize_counters():
    """Int8 quantization pass counters (graphs/nodes quantized, islands
    elided, boundaries calibrated, scales folded, uint8 upgrades,
    offline weight bytes saved), live from mxnet_tpu.analysis.quantize.
    Zeros before the first ``quantize_symbol``/``quantize_model``."""
    return _family("quantize")


def lock_check_counters():
    """Ranked-lock witness counters (out-of-rank acquires, lock-order
    cycles, order-graph edges, self-deadlocks, dropped violation
    records), live from mxnet_tpu.utils.locks. All-zero when
    ``MXNET_LOCK_CHECK`` is off or nothing fired."""
    from .utils import locks as _locks

    return _locks.lock_check_counters()


def sharding_counters():
    """Rule-based SPMD sharding counters (plans built, rules matched/
    unmatched, divisibility fallbacks, fused-step groups compiled under
    a plan, ZeRO-1 groups, sharded serving sessions, sharded-checkpoint
    shard files/saves/restores/reshards), live from mxnet_tpu.sharding.
    Zeros before the first plan scope (MXNET_SHARDING gated)."""
    return _family("sharding")


def _record(domain, name, start_us, dur_us, cat="event", value=None,
            cached=None):
    with _lock:
        if cat == "counter":
            # chrome-trace counter sample: ph 'C' with the value payload
            _events.append({"name": name, "cat": cat, "ph": "C",
                            "ts": start_us, "pid": 0,
                            "args": {name: value}})
        else:
            args = {"domain": domain}
            if cached is not None:
                args["cached"] = bool(cached)
            _events.append({"name": name, "cat": cat, "ph": "X",
                            "ts": start_us, "dur": dur_us, "pid": 0,
                            "tid": threading.get_ident() % 100000,
                            "args": args})
        a = _agg[(domain, name)]
        a["count"] += 1
        if cat == "counter":
            a["total"] = float(value)  # last observed value
            a["min"] = min(a["min"], float(value))
            a["max"] = max(a["max"], float(value))
        else:
            a["total"] += dur_us
            a["min"] = min(a["min"], dur_us)
            a["max"] = max(a["max"], dur_us)


def dump(finished=True, profile_process="worker"):
    """Write accumulated host events as chrome://tracing JSON.

    Since round 18 this routes through ``telemetry.exporter``: the
    legacy profiler event list (Domain/Task scopes, ``record_op``
    dispatch timings) rides along verbatim, the telemetry spans land in
    the same timeline, and every registry family is stamped as one
    counter sample per counter at dump time. Sample names are unchanged
    (``eager_jit_cache/<name>``, ``compile_cache/<name>``, ...) — but
    that ad-hoc ``<family>/<counter>`` naming is DEPRECATED as a parse
    target: it survives this release as a compatibility shim; new
    consumers should read ``telemetry.snapshot()`` (structured) or the
    Prometheus exposition instead of string-splitting sample names."""
    from .telemetry import exporter as _exporter

    fname = _config.get("filename") or "profile.json"
    with _lock:
        legacy = list(_events)
    _exporter.dump_trace(fname, extra_events=legacy)
    return fname


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Aggregate stats table (reference: profiler.py:151 dumps). The
    eager-dispatch cache counters are NOT aggregate rows (they would
    survive `reset` and break the empty-table contract) — read them via
    ``dispatch_cache_counters()`` or the counter samples in ``dump()``."""
    with _lock:
        rows = [(d, n, v["count"], v["total"], v["min"], v["max"],
                 v["total"] / max(v["count"], 1))
                for (d, n), v in _agg.items()]
        if reset:
            _agg.clear()
    rows.sort(key=lambda r: r[3], reverse=not ascending)
    if format == "json":
        return json.dumps([{"domain": d, "name": n, "count": c,
                            "total_us": t, "min_us": mn, "max_us": mx,
                            "avg_us": av}
                           for d, n, c, t, mn, mx, av in rows])
    lines = ["%-20s %-30s %8s %12s %10s %10s %10s" %
             ("Domain", "Name", "Count", "Total(us)", "Min(us)",
              "Max(us)", "Avg(us)")]
    for d, n, c, t, mn, mx, av in rows:
        lines.append("%-20s %-30s %8d %12.1f %10.1f %10.1f %10.1f"
                     % (d, n, c, t, mn, mx, av))
    return "\n".join(lines)


class Domain:
    """Reference: profiler.py Domain — namespace for profiler objects."""

    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_event(self, name):
        return Event(name, domain=self)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Scoped:
    def __init__(self, domain, name):
        self.domain = domain.name if isinstance(domain, Domain) else \
            str(domain)
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is None:
            return
        dur = (time.perf_counter() - self._t0) * 1e6
        _record(self.domain, self.name, self._t0 * 1e6, dur,
                cat=type(self).__name__.lower())
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Task(_Scoped):
    pass


class Frame(_Scoped):
    pass


class Event(_Scoped):
    def __init__(self, name, domain=None):
        super().__init__(domain or Domain("event"), name)


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain = domain.name
        self.name = name
        self.value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self.value = value
        _record(self.domain, self.name, time.perf_counter() * 1e6, 0,
                cat="counter", value=value)

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self


class Marker:
    def __init__(self, domain, name):
        self.domain = domain.name
        self.name = name

    def mark(self, scope="process"):
        _record(self.domain, self.name, time.perf_counter() * 1e6, 0,
                cat="marker")
