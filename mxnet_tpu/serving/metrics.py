"""Serving metrics: latency histograms, queue depth, batch shape, QPS.

The observability spine of the serving subsystem (reference analog: the
MXNet model-server's `/metrics` endpoint and per-request logs). One
process-wide :class:`ServingMetrics` registry backs every
``InferenceSession`` / ``DynamicBatcher`` / ``ModelServer`` instance, so
``profiler.serving_counters()`` (and the ``serving/*`` counter samples in
``profiler.dump()``) always reflect the whole process — the same pattern
as the dispatch-cache and fused-step counters.

Three measurement families:

- **Latency histograms** (log-spaced, fixed bounds): end-to-end request
  latency (submit -> result), model execution latency (one coalesced
  batch through the session), and time-to-flush (how long the batcher
  held the first request of a batch). Quantiles (p50/p95/p99) are read
  by linear interpolation inside the owning bucket — cheap enough to
  compute per scrape, never on the request path.
- **Counters**: requests/responses/failures/invalid/timeouts/rejected
  (backpressure), batches, inline executions (pass-through or
  post-close), warm-start disk hits vs fresh compiles, padded vs true
  rows (bucket padding overhead).
- **Gauges**: live queue depth (probed from the owning batcher at read
  time, never sampled on the hot path), SLO headroom (probed from the
  owning admission controller), and 60-second completion windows for
  QPS and goodput (completions that met their deadline).

Round 13 adds the SLO dimension: every request carries a priority
class (:data:`SLO_CLASSES`), and the registry keeps per-class counters
plus per-class ROLLING latency histograms (:class:`RollingHistogram`) —
cumulative histograms never forget an overload spike, but admission
control needs a p99 that recovers once the spike passes, so headroom
is computed over a sliding window instead.

Round 16 adds the incremental-decode dimension: ``decode_steps``
(fused continuous-batching step executions), ``evictions`` /
``resumed_sessions`` (session-state lifecycle), and a
``slot_occupancy`` gauge probed from live :class:`SessionStateStore`
instances — all of which flow through ``serving_counters()``,
``profiler.dump()`` samples, and the Prometheus families for free.

Round 21 adds the KV page-pool dimension for paged stores:
``kv_pages_total`` / ``kv_pages_used`` / ``kv_pages_per_session_p50``
/ ``_p99`` / ``kv_bytes`` gauges, probed from each paged
``SessionStateStore`` at read time (same pattern as occupancy).
"""
from __future__ import annotations

import bisect
import threading
import time
from collections import deque

from ..utils import locks as _locks

__all__ = ["LatencyHistogram", "RollingHistogram", "ServingMetrics",
           "METRICS", "SLO_CLASSES", "serving_stats",
           "reset_serving_counters", "prometheus_text"]

#: request priority classes, highest priority first. "critical" is the
#: protected class (admission control never sheds it); "best_effort"
#: sheds first when headroom runs out. Defined here (the lowest layer
#: of serving/) so batcher, admission and repository all agree.
SLO_CLASSES = ("critical", "standard", "best_effort")

#: log-spaced latency bucket upper bounds, seconds (last bucket +inf)
LATENCY_BOUNDS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

#: batch-size bucket upper bounds, rows (last bucket +inf)
BATCH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)

_QPS_WINDOW_S = 60.0


class LatencyHistogram:
    """Fixed-bound histogram with interpolated quantiles.

    Bounds are upper edges; one overflow bucket catches everything past
    the last bound. ``observe`` is O(log buckets) (bisect) under the
    shared registry lock — the caller holds it."""

    __slots__ = ("bounds", "counts", "total", "sum")

    def __init__(self, bounds=LATENCY_BOUNDS_S):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.total = 0
        self.sum = 0.0

    def observe(self, value):
        self.counts[bisect.bisect_left(self.bounds, float(value))] += 1
        self.total += 1
        self.sum += float(value)

    def quantile(self, q):
        """Value at quantile ``q`` (0..1), linearly interpolated inside
        the owning bucket; 0.0 when empty. The overflow bucket reports
        its lower edge (there is no upper edge to interpolate toward)."""
        if self.total == 0:
            return 0.0
        rank = q * self.total
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                if i >= len(self.bounds):
                    return self.bounds[-1]
                hi = self.bounds[i]
                frac = (rank - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return self.bounds[-1]

    def snapshot(self):
        return {"total": self.total, "sum": self.sum,
                "counts": list(self.counts)}


class RollingHistogram:
    """Sliding-window histogram: two :class:`LatencyHistogram` frames
    rotated every ``window_s / 2``; reads merge both frames, so a
    quantile covers the last ``window_s/2 .. window_s`` seconds of
    observations and recovers once a spike ages out. The caller (the
    registry) holds the lock and passes ``now``."""

    __slots__ = ("bounds", "_half", "_cur", "_prev", "_flip_at")

    def __init__(self, bounds=LATENCY_BOUNDS_S, window_s=20.0):
        self.bounds = tuple(float(b) for b in bounds)
        self._half = float(window_s) / 2.0
        self._cur = LatencyHistogram(self.bounds)
        self._prev = LatencyHistogram(self.bounds)
        self._flip_at = None  # armed on first observe

    def _rotate(self, now):
        if self._flip_at is None:
            self._flip_at = now + self._half
            return
        if now < self._flip_at:
            return
        # one flip when we're late by less than a frame; both frames
        # are stale past that, so start clean instead of promoting
        self._prev = self._cur if now - self._flip_at < self._half \
            else LatencyHistogram(self.bounds)
        self._cur = LatencyHistogram(self.bounds)
        self._flip_at = now + self._half

    def observe(self, value, now):
        self._rotate(now)
        self._cur.observe(value)

    @property
    def total(self):
        return self._cur.total + self._prev.total

    def quantile(self, q, now):
        self._rotate(now)
        if self._prev.total == 0:
            return self._cur.quantile(q)
        merged = LatencyHistogram(self.bounds)
        merged.counts = [a + b for a, b in zip(self._cur.counts,
                                               self._prev.counts)]
        merged.total = self._cur.total + self._prev.total
        return merged.quantile(q)


_COUNTER_NAMES = (
    "requests", "responses", "failures", "invalid", "timeouts",
    "rejected", "batches", "inline", "warm_disk_hits", "warm_compiles",
    "bucket_execs", "padded_rows", "true_rows",
    # round 13: SLO-aware admission + model repository
    "shed", "deadline_met", "canary_requests", "canary_failures",
    "canary_fallbacks", "canary_deploys", "canary_promotions",
    "canary_rollbacks", "model_swaps",
    # round 16: stateful continuous-batching decode
    "decode_steps", "evictions", "resumed_sessions",
    # round 19: the MXNET_QUANTIZE_SHADOW accuracy gate
    "canary_shadow_checks", "canary_shadow_mismatches",
)

#: the per-SLO-class slice of the counters (suffixed ``:<class>``)
_CLASS_COUNTER_NAMES = ("requests", "responses", "failures",
                        "timeouts", "shed")


class ServingMetrics:
    """Process-wide serving metric registry (single lock; every
    mutation is a couple of integer bumps, cheap enough for the request
    path)."""

    def __init__(self):
        # guards: _depth_probes, _headroom_probes, _occupancy_probes, _page_probes
        self._lock = _locks.RankedLock("serving.metrics")
        self._reset_locked()
        self._depth_probes = {}  # token -> callable() -> int
        self._headroom_probes = {}  # token -> callable() -> float
        self._occupancy_probes = {}  # token -> callable() -> int
        self._page_probes = {}  # token -> callable() -> dict

    def _reset_locked(self):
        self.counters = dict.fromkeys(_COUNTER_NAMES, 0)
        self.class_counters = {
            c: dict.fromkeys(_CLASS_COUNTER_NAMES, 0)
            for c in SLO_CLASSES}
        self.request_latency = LatencyHistogram()
        self.exec_latency = LatencyHistogram()
        self.flush_wait = LatencyHistogram()
        self.batch_rows = LatencyHistogram(BATCH_BOUNDS)
        self.class_latency = {c: RollingHistogram() for c in SLO_CLASSES}
        self._completions = deque()  # monotonic stamps, QPS window
        self._goodput = deque()  # stamps of deadline-met completions
        self._started = time.monotonic()

    # -- mutation (request path) -------------------------------------

    def bump(self, name, n=1):
        with self._lock:
            self.counters[name] += n

    def bump_class(self, name, slo_class, n=1):
        """Bump the per-class slice of counter ``name`` (unknown
        classes fold into "standard" rather than KeyError — the
        request path must never crash on a label)."""
        with self._lock:
            per = self.class_counters.get(slo_class) or \
                self.class_counters["standard"]
            per[name] += n

    def observe_request(self, latency_s, failed=False, timed_out=False,
                        slo_class=None, met_deadline=None):
        """One completed (or failed) request. ``slo_class`` routes the
        observation into the per-class counters and rolling histogram;
        ``met_deadline`` feeds goodput (None means "met iff it didn't
        fail" — callers without a deadline notion stay correct)."""
        now = time.monotonic()
        met = (not failed) if met_deadline is None else bool(met_deadline)
        with self._lock:
            self.counters["responses"] += 1
            if failed:
                self.counters["failures"] += 1
            if timed_out:
                self.counters["timeouts"] += 1
            if met:
                self.counters["deadline_met"] += 1
                self._goodput.append(now)
            self.request_latency.observe(latency_s)
            if slo_class is not None:
                per = self.class_counters.get(slo_class) or \
                    self.class_counters["standard"]
                per["responses"] += 1
                if failed:
                    per["failures"] += 1
                if timed_out:
                    per["timeouts"] += 1
                hist = self.class_latency.get(slo_class) or \
                    self.class_latency["standard"]
                hist.observe(latency_s, now)
            self._completions.append(now)
            self._trim_window_locked(now)

    def observe_shed(self, slo_class):
        """One request shed by admission control (fast 503 at submit —
        it never entered the queue)."""
        with self._lock:
            self.counters["shed"] += 1
            per = self.class_counters.get(slo_class) or \
                self.class_counters["standard"]
            per["shed"] += 1

    def observe_batch(self, rows, exec_s):
        """One session.predict execution (bucket_execs counts the
        underlying bucket-executable invocations separately — a
        chunked oversized predict runs several per batch)."""
        with self._lock:
            self.counters["batches"] += 1
            self.batch_rows.observe(rows)
            self.exec_latency.observe(exec_s)

    def observe_flush(self, wait_s):
        """Time the batcher held a batch's FIRST request before
        executing (the latency cost of coalescing)."""
        with self._lock:
            self.flush_wait.observe(wait_s)

    def _trim_window_locked(self, now):
        cutoff = now - _QPS_WINDOW_S
        while self._completions and self._completions[0] < cutoff:
            self._completions.popleft()
        while self._goodput and self._goodput[0] < cutoff:
            self._goodput.popleft()

    # -- admission-control reads (request path, cheap) ----------------

    def exec_estimate_s(self):
        """p50 model-execution latency in seconds — the batcher's
        flush margin for deadline-aware coalescing. 0.0 before any
        execution (no margin is the right cold-start answer)."""
        with self._lock:
            return self.exec_latency.quantile(0.50)

    def class_latency_s(self, slo_class, q=0.99):
        """Rolling-window latency quantile for one SLO class, seconds
        (0.0 with no recent traffic)."""
        now = time.monotonic()
        with self._lock:
            hist = self.class_latency.get(slo_class)
            return hist.quantile(q, now) if hist is not None else 0.0

    # -- gauges -------------------------------------------------------

    def register_depth_probe(self, probe):
        """Register a live queue-depth callable (a batcher's
        ``qsize``); returns a token for :meth:`unregister_depth_probe`.
        Probed at read time only — depth is never sampled on the
        request path."""
        token = object()
        with self._lock:
            self._depth_probes[token] = probe
        return token

    def unregister_depth_probe(self, token):
        with self._lock:
            self._depth_probes.pop(token, None)

    def queue_depth(self):
        with self._lock:
            probes = list(self._depth_probes.values())
        depth = 0
        for p in probes:
            try:
                depth += int(p())
            except Exception:  # graft-lint: allow(L501)
                pass
        return depth

    def register_headroom_probe(self, probe):
        """Register a live SLO-headroom callable (an
        AdmissionController's ``headroom``); returns a token for
        :meth:`unregister_headroom_probe`."""
        token = object()
        with self._lock:
            self._headroom_probes[token] = probe
        return token

    def unregister_headroom_probe(self, token):
        with self._lock:
            self._headroom_probes.pop(token, None)

    def register_occupancy_probe(self, probe):
        """Register a live session-slot occupancy callable (a
        ``SessionStateStore``'s live-session count); returns a token
        for :meth:`unregister_occupancy_probe`. Probed at read time
        only, like queue depth."""
        token = object()
        with self._lock:
            self._occupancy_probes[token] = probe
        return token

    def unregister_occupancy_probe(self, token):
        with self._lock:
            self._occupancy_probes.pop(token, None)

    def slot_occupancy(self):
        """Total live sessions across registered state stores."""
        with self._lock:
            probes = list(self._occupancy_probes.values())
        occ = 0
        for p in probes:
            try:
                occ += int(p())
            except Exception:  # graft-lint: allow(L501)
                pass
        return occ

    def register_page_probe(self, probe):
        """Register a KV page-pool sampler (a paged
        ``SessionStateStore``); the callable returns a dict with
        ``pages_total`` / ``pages_used`` / ``pages_per_session``
        (per-live-session page counts) / ``kv_bytes``. Probed at read
        time only. Returns a token for
        :meth:`unregister_page_probe`."""
        token = object()
        with self._lock:
            self._page_probes[token] = probe
        return token

    def unregister_page_probe(self, token):
        with self._lock:
            self._page_probes.pop(token, None)

    def page_stats(self):
        """Aggregated KV page-pool gauges across registered paged
        stores: totals plus p50/p99 pages-per-live-session (0 with no
        paged store or no live sessions)."""
        with self._lock:
            probes = list(self._page_probes.values())
        total = used = kv_bytes = 0
        per = []
        for p in probes:
            try:
                st = p()
                total += int(st.get("pages_total", 0))
                used += int(st.get("pages_used", 0))
                kv_bytes += int(st.get("kv_bytes", 0))
                per.extend(int(v) for v in
                           st.get("pages_per_session", ()))
            except Exception:  # graft-lint: allow(L501)
                pass
        per.sort()

        def pct(q):
            if not per:
                return 0
            return per[min(int(q * (len(per) - 1) + 0.5),
                           len(per) - 1)]

        return {"kv_pages_total": total, "kv_pages_used": used,
                "kv_pages_per_session_p50": pct(0.50),
                "kv_pages_per_session_p99": pct(0.99),
                "kv_bytes": kv_bytes}

    def slo_headroom(self):
        """Minimum live headroom across registered admission
        controllers, 0..1 (1.0 with none registered — no controller
        means nothing is at risk that we can see)."""
        with self._lock:
            probes = list(self._headroom_probes.values())
        head = 1.0
        for p in probes:
            try:
                head = min(head, float(p()))
            except Exception:  # graft-lint: allow(L501)
                pass
        return max(head, 0.0)

    # -- reading ------------------------------------------------------

    def snapshot(self):
        """Flat numeric dict — the ``profiler.serving_counters()``
        surface. Latencies are reported in milliseconds (matching the
        ``*_ms`` lower-is-better convention of bench_compare)."""
        now = time.monotonic()
        with self._lock:
            st = dict(self.counters)
            self._trim_window_locked(now)
            window = min(_QPS_WINDOW_S, max(now - self._started, 1e-9))
            st["qps_60s"] = round(len(self._completions) / window, 3)
            st["goodput_rps"] = round(len(self._goodput) / window, 3)
            st["shed_rate"] = round(
                st["shed"] / st["requests"], 4) if st["requests"] else 0.0
            for prefix, hist in (("latency", self.request_latency),
                                 ("exec", self.exec_latency)):
                st[f"{prefix}_p50_ms"] = round(
                    hist.quantile(0.50) * 1e3, 3)
                st[f"{prefix}_p95_ms"] = round(
                    hist.quantile(0.95) * 1e3, 3)
                st[f"{prefix}_p99_ms"] = round(
                    hist.quantile(0.99) * 1e3, 3)
            for cls in SLO_CLASSES:
                for name, v in self.class_counters[cls].items():
                    st[f"{name}:{cls}"] = v
                hist = self.class_latency[cls]
                st[f"latency_p50_ms:{cls}"] = round(
                    hist.quantile(0.50, now) * 1e3, 3)
                st[f"latency_p99_ms:{cls}"] = round(
                    hist.quantile(0.99, now) * 1e3, 3)
            st["batch_rows_mean"] = round(
                self.batch_rows.sum / self.batch_rows.total, 3) \
                if self.batch_rows.total else 0.0
            st["pad_ratio"] = round(
                st["padded_rows"] / st["true_rows"], 4) \
                if st["true_rows"] else 0.0
        st["queue_depth"] = self.queue_depth()
        st["slo_headroom"] = round(self.slo_headroom(), 4)
        st["slot_occupancy"] = self.slot_occupancy()
        st.update(self.page_stats())
        return st

    def reset(self):
        """Zero counters and histograms (tests, benchmarks). Depth
        probes survive — they belong to live batchers, not to the
        sample window."""
        with self._lock:
            self._reset_locked()

    def prometheus_text(self):
        """Prometheus text exposition of the registry — the
        ``/metrics`` endpoint body."""
        lines = []

        def emit(name, value, help_=None, typ="counter", labels=""):
            if help_:
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} {typ}")
            lines.append(f"{name}{labels} {value}")

        now = time.monotonic()
        with self._lock:
            counters = dict(self.counters)
            class_counters = {c: dict(v)
                              for c, v in self.class_counters.items()}
            class_p99 = {c: self.class_latency[c].quantile(0.99, now)
                         for c in SLO_CLASSES}
            hists = [("mxnet_serving_request_latency_seconds",
                      self.request_latency.snapshot(),
                      self.request_latency.bounds,
                      "end-to-end request latency"),
                     ("mxnet_serving_exec_latency_seconds",
                      self.exec_latency.snapshot(),
                      self.exec_latency.bounds,
                      "model execution latency per coalesced batch"),
                     ("mxnet_serving_batch_rows",
                      self.batch_rows.snapshot(),
                      self.batch_rows.bounds,
                      "rows per executed batch")]
        for name, value in sorted(counters.items()):
            emit(f"mxnet_serving_{name}_total", value,
                 help_=f"serving counter {name}")
        for name in _CLASS_COUNTER_NAMES:
            fam = f"mxnet_serving_class_{name}_total"
            lines.append(f"# HELP {fam} per-SLO-class counter {name}")
            lines.append(f"# TYPE {fam} counter")
            for cls in SLO_CLASSES:
                lines.append(f'{fam}{{slo_class="{cls}"}} '
                             f'{class_counters[cls][name]}')
        fam = "mxnet_serving_class_latency_p99_seconds"
        lines.append(f"# HELP {fam} rolling-window p99 request latency")
        lines.append(f"# TYPE {fam} gauge")
        for cls in SLO_CLASSES:
            lines.append(f'{fam}{{slo_class="{cls}"}} {class_p99[cls]}')
        emit("mxnet_serving_queue_depth", self.queue_depth(),
             help_="live batcher queue depth", typ="gauge")
        emit("mxnet_serving_slo_headroom", self.slo_headroom(),
             help_="min live SLO headroom across admission controllers "
                   "(0..1)", typ="gauge")
        emit("mxnet_serving_slot_occupancy", self.slot_occupancy(),
             help_="live sessions holding server-side state slots",
             typ="gauge")
        page_help = {
            "kv_pages_total": "physical KV pages across paged stores",
            "kv_pages_used": "allocated KV pages across paged stores",
            "kv_pages_per_session_p50":
                "median pages held per live session",
            "kv_pages_per_session_p99":
                "p99 pages held per live session",
            "kv_bytes": "bytes held by allocated KV pages"}
        for name, value in sorted(self.page_stats().items()):
            emit(f"mxnet_serving_{name}", value,
                 help_=page_help.get(name, name), typ="gauge")
        try:
            from ..kernels import counters as _fusion_counters

            fam = "mxnet_fusion"
            for name, value in sorted(_fusion_counters().items()):
                emit(f"{fam}_{name}_total", value,
                     help_=f"fusion clustering counter {name}")
        except Exception:  # graft-lint: allow(L501)
            pass  # fusion counters are best-effort on this surface
        for name, snap, bounds, help_ in hists:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} histogram")
            cum = 0
            for b, c in zip(bounds, snap["counts"]):
                cum += c
                lines.append(f'{name}_bucket{{le="{b}"}} {cum}')
            lines.append(
                f'{name}_bucket{{le="+Inf"}} {snap["total"]}')
            lines.append(f"{name}_sum {snap['sum']}")
            lines.append(f"{name}_count {snap['total']}")
        return "\n".join(lines) + "\n"


#: the process-wide registry every serving component reports into
METRICS = ServingMetrics()


def serving_stats():
    """Flat numeric serving counters (the profiler surface)."""
    return METRICS.snapshot()


def reset_serving_counters():
    """Zero the process-wide serving counters (tests, benchmarks)."""
    METRICS.reset()


def prometheus_text():
    """Prometheus text rendering of the process-wide registry."""
    return METRICS.prometheus_text()
