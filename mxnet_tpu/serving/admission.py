"""SLO-aware admission control: shed best-effort load BEFORE it queues.

The overload valve of the serving subsystem (reference analog: every
production front door — GFE/Envoy admission, SageMaker's 503 +
``Retry-After``). Under sustained overload a FIFO queue converts every
request into a timeout: work waits out most of its deadline, then
executes (wasted capacity) or expires (wasted wait). The fix is
admission control at ``submit()`` — when queue-depth / p99 headroom
says the high-priority SLO is at risk, low-priority requests get an
immediate :class:`ShedLoad` (HTTP 503 with ``Retry-After``) instead of
a doomed wait.

Mechanics
---------
Requests carry one of :data:`SLO_CLASSES` (``critical`` > ``standard``
> ``best_effort``). The controller computes **headroom** in [0, 1] as
the minimum of two signals:

- *queue headroom*: ``1 - depth / capacity`` over the batcher's bounded
  queues — the leading indicator (fills before latency degrades);
- *latency headroom*: ``1 - p99 / slo_target`` where p99 is the
  ROLLING-window latency of the protected (highest-priority) class
  with recent traffic — the ground truth (recovers once a spike ages
  out, unlike a cumulative histogram).

Classes shed at graduated thresholds: ``best_effort`` below
``MXNET_SERVING_SHED_HEADROOM``, ``standard`` below half of it, and
``critical`` is never shed by admission (only queue-full
backpressure can reject it). Deterministic testing rides the round-12
fault grammar: ``MXNET_FAULT_PLAN=serving_admission:...`` forces the
shed path for sheddable classes regardless of headroom.

Round 16 adds a third signal for STATEFUL serving: *slot headroom* —
``1 - occupancy / slots`` over the session's state pool. It is folded
into the decision only for a submit that would ALLOCATE a new state
slot (``allocates_state=True``): steps of already-live streams hold
their slot and must not be shed by pool pressure, but admitting a new
stream into a nearly-full pool would evict someone's state to serve
it — exactly the trade admission control exists to refuse.
"""
from __future__ import annotations

import time

from ..resilience import faults as _faults
from .batcher import ServerBusy
from .metrics import METRICS, SLO_CLASSES

__all__ = ["AdmissionController", "ShedLoad", "SLO_CLASSES",
           "normalize_class", "admission_enabled"]

_PRIORITY = {c: i for i, c in enumerate(SLO_CLASSES)}


class ShedLoad(ServerBusy):
    """Request shed by admission control (HTTP 503). Carries
    ``retry_after_s`` so the HTTP layer can emit ``Retry-After`` and a
    well-behaved client backs off instead of hammering."""

    def __init__(self, message, retry_after_s=0.25):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


def normalize_class(slo_class):
    """Default None to "standard"; reject unknown labels loudly (a
    typo'd class silently landing in best_effort would be shed —
    exactly the bug a 400 at the boundary prevents)."""
    if slo_class is None:
        return "standard"
    if slo_class not in _PRIORITY:
        raise ValueError(
            f"unknown SLO class {slo_class!r}; expected one of "
            f"{SLO_CLASSES}")
    return slo_class


def admission_enabled():
    """MXNET_SERVING_ADMISSION gate (default on). Off, every class is
    plain FIFO-with-backpressure — the round-10 behavior."""
    from .. import env as _env

    return _env.get_bool("MXNET_SERVING_ADMISSION", True)


class AdmissionController:
    """Per-batcher admission decisions + the /healthz headroom signal.

    One controller per :class:`~mxnet_tpu.serving.batcher.DynamicBatcher`
    (constructed by it); registers a headroom probe on the process
    metrics registry so ``slo_headroom`` in ``serving_counters()`` and
    ``/metrics`` always reflects the live minimum."""

    def __init__(self, batcher, slo_ms=None, shed_headroom=None,
                 retry_after_ms=None, enabled=None):
        from .. import env as _env

        self._batcher = batcher
        self._slo_s = float(
            slo_ms if slo_ms is not None else
            _env.get_float("MXNET_SERVING_SLO_MS", 100.0)) / 1e3
        self._shed_headroom = float(
            shed_headroom if shed_headroom is not None else
            _env.get_float("MXNET_SERVING_SHED_HEADROOM", 0.15))
        self._retry_after_s = float(
            retry_after_ms if retry_after_ms is not None else
            _env.get_float("MXNET_SERVING_RETRY_AFTER_MS", 250.0)) / 1e3
        self.enabled = admission_enabled() if enabled is None else \
            bool(enabled)
        self._probe_token = METRICS.register_headroom_probe(
            self.headroom)

    # -- signals -------------------------------------------------------

    def _queue_headroom(self):
        cap = max(self._batcher.queue_capacity(), 1)
        return 1.0 - min(self._batcher.qsize(), cap) / cap

    def _latency_headroom(self):
        # protect the highest-priority class with recent traffic; with
        # none, the overall rolling picture would lag — report full
        # headroom instead (no traffic means no SLO at risk)
        for cls in SLO_CLASSES:
            if METRICS.class_latency[cls].total:
                p99 = METRICS.class_latency_s(cls, 0.99)
                return 1.0 - min(p99 / self._slo_s, 1.0)
        return 1.0

    def _slot_headroom(self):
        """Free fraction of the session state pool (1.0 for stateless
        batchers — no pool, nothing to protect). A paged store folds
        in its KV page pool too: slots may be plentiful while every
        page is spoken for, and a new stream needs at least one."""
        store = getattr(getattr(self._batcher, "session", None),
                        "state_store", None)
        if store is None:
            return 1.0
        slots = max(store.num_slots, 1)
        head = 1.0 - min(store.occupancy, slots) / slots
        pages = getattr(store, "page_headroom", None)
        if callable(pages):
            ph = pages()
            if ph is not None:
                head = min(head, ph)
        return head

    def headroom(self):
        """Live SLO headroom in [0, 1]: min(queue, latency) signals.
        1.0 = idle, 0.0 = the protected SLO is already blown."""
        return max(min(self._queue_headroom(),
                       self._latency_headroom()), 0.0)

    def shed_threshold(self, slo_class):
        """Headroom floor below which ``slo_class`` sheds: graduated
        by priority (best_effort at the full knob, standard at half,
        critical never)."""
        pri = _PRIORITY[slo_class]
        return self._shed_headroom * pri / (len(SLO_CLASSES) - 1)

    # -- the decision (request path) -----------------------------------

    def check(self, slo_class, allocates_state=False):
        """Admit or raise :class:`ShedLoad`. Called by
        ``DynamicBatcher.submit`` after validation, before enqueue —
        a shed request never occupies a queue slot.
        ``allocates_state=True`` (a stateful submit opening a NEW
        stream) additionally folds slot headroom into the decision, so
        sheddable classes stop claiming state slots before the pool
        starts evicting live streams to make room."""
        if not self.enabled:
            return
        try:
            _faults.maybe_fail("serving_admission")
        except Exception as e:
            # an injected admission fault forces the shed path (for
            # critical it downgrades to headroom-based shedding below
            # — the protected class is never force-shed either)
            if _PRIORITY[slo_class] > 0:
                self._shed(slo_class, forced=True, cause=e)
        if _PRIORITY[slo_class] == 0:
            return  # protected class: backpressure only
        head = self.headroom()
        if allocates_state:
            head = min(head, self._slot_headroom())
        if head < self.shed_threshold(slo_class):
            self._shed(slo_class, headroom=head)

    def _shed(self, slo_class, headroom=None, forced=False, cause=None):
        METRICS.observe_shed(slo_class)
        detail = "fault-injected shed" if forced else (
            f"SLO headroom {headroom:.3f} below "
            f"{self.shed_threshold(slo_class):.3f}")
        err = ShedLoad(
            f"request shed ({slo_class}): {detail}; retry after "
            f"{self._retry_after_s * 1e3:.0f} ms",
            retry_after_s=self._retry_after_s)
        raise err from cause

    # -- observability -------------------------------------------------

    def snapshot(self):
        """The /healthz ``slo`` block: live headroom, its component
        signals, per-class shed thresholds and rolling p99s."""
        qh, lh = self._queue_headroom(), self._latency_headroom()
        return {
            "enabled": self.enabled,
            "headroom": round(max(min(qh, lh), 0.0), 4),
            "queue_headroom": round(max(qh, 0.0), 4),
            "latency_headroom": round(max(lh, 0.0), 4),
            "slot_headroom": round(max(self._slot_headroom(), 0.0), 4),
            "slo_ms": self._slo_s * 1e3,
            "shedding": [c for c in SLO_CLASSES if _PRIORITY[c] > 0 and
                         min(qh, lh) < self.shed_threshold(c)],
            "p99_ms": {c: round(METRICS.class_latency_s(c, 0.99) * 1e3,
                                3) for c in SLO_CLASSES},
        }

    def close(self):
        METRICS.unregister_headroom_probe(self._probe_token)
