"""Replica fleet behind one front door: consistent-hash routing,
bundle-warm lifecycle, and live-session drain (round 23).

One serving replica (rounds 10-21) answers on one port. A fleet is N
of them behind a :class:`FleetRouter` — a routing front end that owns
the client-facing HTTP surface and fans out to replica processes
(reference analog: MXNet model-server behind a GFE/Envoy front door;
SageMaker multi-instance endpoints). The router is stdlib-only like
:class:`~mxnet_tpu.serving.server.ModelServer` and testable on CPU
with plain subprocesses.

What the router owns
--------------------
- **Consistent-hash session affinity.** Stateful decode streams carry
  state in ONE replica's paged KV pool (round 21), so every step of a
  stream must land on the replica holding its slot. Session ids hash
  onto a ring of ``MXNET_FLEET_VNODES`` virtual nodes per replica;
  the first routed step pins ``sid -> replica`` in an affinity table
  (the ring only *seeds* placement — drains move pins without moving
  hashes). Stateless requests ignore the ring and go to the
  least-loaded serving replica (gossiped queue depth).
- **Fleet-wide SLO admission.** The round-13 ladder
  (:class:`~mxnet_tpu.serving.admission.AdmissionController`) runs
  router-side against the AGGREGATE queue depth/capacity gossiped via
  each replica's existing ``/healthz`` — a best-effort request is
  shed at the front door before it burns a connection to a busy
  replica. ``X-SLO-Class`` / ``X-Timeout-Ms`` headers are honored
  fleet-wide and forwarded verbatim.
- **Replica lifecycle.** *Join*: a replica spawned via
  :func:`spawn_replica` warms from a bundle
  (:func:`~mxnet_tpu.artifact.import_bundle` + the round-20 remote
  compile cache) so a joining replica NEVER compiles; the router
  probes ``/healthz`` until warm before ring entry. *Drain*: stop
  routing new sessions, wait for the queue to empty, migrate live
  decode streams to ring successors via the round-16/21
  ``export_state``/``restore_state`` dense-row form (which crosses
  paging geometries), then remove — zero dropped sessions. *Eject*: a
  replica whose health probe trips its per-replica
  :class:`~mxnet_tpu.resilience.breaker.CircuitBreaker` (round 12)
  leaves the ring until probes succeed again.
- **Fleet-level canary.** ``MXNET_SERVING_CANARY_FRACTION`` of
  non-critical stateless traffic is counter-routed to canary-flagged
  replicas as a SHADOW PAIR: the incumbent answer is always computed,
  the canary answer only replaces it when the round-19 shadow
  accuracy gate (``_rel_deviation`` vs ``MXNET_QUANTIZE_SHADOW_TOL``)
  passes — so a bad canary produces zero client-visible failures. The
  fleet canary breaker leaving "closed" rolls ALL traffic back to
  incumbents (``canary_rollbacks``).

Observability: ``mxnet_fleet_*`` counters plus per-replica labeled
series (``mxnet_fleet_replica_up{replica="r0"}``) ride the unified
``/metrics`` exposition; ``X-Request-Id`` trace ids propagate
router -> replica so one client request joins both traces.
"""
from __future__ import annotations

import bisect
import hashlib
import json
import logging
import os
import pickle
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..resilience.breaker import CircuitBreaker
from ..telemetry import metrics as _tmetrics
from ..telemetry import tracer as _telem
from ..utils import locks as _locks
from .admission import AdmissionController, ShedLoad, normalize_class
from .repository import _rel_deviation

__all__ = ["FleetRouter", "Replica", "ReplicaProcess", "spawn_replica",
           "fleet_counters", "reset_fleet_counters"]

_MAX_BODY = 64 * 1024 * 1024  # matches the replica-side bound

#: fleet router counters (telemetry registry: ride profiler.dump() and
#: the unified /metrics exposition)
_FLEET = _tmetrics.counter_family("fleet", {
    "requests": 0,          # POSTs reaching the router's routing logic
    "routed": 0,            # replies served from a replica
    "shed": 0,              # fleet-wide admission 503s
    "no_replica": 0,        # 503: no serving replica available
    "retries": 0,           # stateless re-route after transport failure
    "transport_errors": 0,  # failed replica connections (request path)
    "blocked_on_drain": 0,  # stateful requests parked on a drain event
    "drain_timeouts": 0,    # parked requests that gave up (503)
    "joins": 0, "ejections": 0, "recoveries": 0, "probes": 0,
    "drains": 0, "drained_sessions": 0, "affinity_moves": 0,
    "canary_requests": 0, "canary_fallbacks": 0,
    "shadow_checks": 0, "shadow_mismatches": 0, "canary_rollbacks": 0})

#: live routers for the per-replica exposition (weak: a dropped router
#: must not be kept alive by /metrics)
_ROUTERS = weakref.WeakSet()


def fleet_counters():
    return dict(_FLEET.snapshot())


def reset_fleet_counters():
    _FLEET.reset()


class _TransportError(Exception):
    """A replica connection failed (refused/reset/timeout) — distinct
    from an HTTP error status, which is a ROUTED reply to pass
    through."""


# -- consistent-hash ring ---------------------------------------------------


def _hash64(key):
    """Stable 64-bit point on the ring (sha256 prefix — NOT ``hash()``,
    which is salted per process and would re-shard every restart)."""
    return int.from_bytes(
        hashlib.sha256(key.encode("utf-8")).digest()[:8], "big")


class _HashRing:
    """Consistent-hash ring with virtual nodes. ``vnodes`` points per
    replica smooth the key distribution; adding or removing one
    replica only remaps the keys that hashed to its arcs (the
    property that makes join/drain cheap). Not thread-safe — the
    router serializes access under its lock."""

    def __init__(self, vnodes):
        self._vnodes = max(int(vnodes), 1)
        self._points = []  # sorted [(point, name)]
        self._names = set()

    def add(self, name):
        if name in self._names:
            return
        self._names.add(name)
        for i in range(self._vnodes):
            bisect.insort(self._points, (_hash64(f"{name}#{i}"), name))

    def remove(self, name):
        if name not in self._names:
            return
        self._names.discard(name)
        self._points = [p for p in self._points if p[1] != name]

    def __contains__(self, name):
        return name in self._names

    def __len__(self):
        return len(self._names)

    def lookup(self, key):
        """The replica owning ``key``: first ring point clockwise from
        the key's hash (wrapping). None on an empty ring."""
        if not self._points:
            return None
        i = bisect.bisect_right(self._points,
                                (_hash64(key), "\uffff"))
        return self._points[i % len(self._points)][1]


# -- replica record ---------------------------------------------------------


class Replica:
    """Router-side record of one replica: address, lifecycle state
    (``joining -> serving -> draining -> left``, with ``ejected`` as
    the probe-breaker detour), the last gossiped health document, and
    the per-replica probe breaker. Mutated only under the router
    lock."""

    __slots__ = ("name", "url", "canary", "state", "breaker", "health",
                 "warm", "depth", "capacity", "requests", "process")

    def __init__(self, name, url, canary=False, process=None):
        self.name = name
        self.url = url.rstrip("/")
        self.canary = bool(canary)
        self.state = "joining"
        self.breaker = CircuitBreaker(name=f"fleet.{name}")
        self.health = {}
        self.warm = False
        self.depth = 0
        self.capacity = 1
        self.requests = 0
        self.process = process  # optional ReplicaProcess (owned)

    def snapshot(self):
        return {"name": self.name, "url": self.url,
                "canary": self.canary, "state": self.state,
                "warm": self.warm, "queue_depth": self.depth,
                "queue_capacity": self.capacity,
                "requests": self.requests,
                "breaker": self.breaker.state}


class _FleetLoad:
    """Quacks like the batcher slice ``AdmissionController`` reads —
    aggregate gossiped queue depth/capacity over serving replicas.
    ``session`` stays None: slot headroom is a per-replica concern
    (each replica's own admission already folds it in)."""

    session = None

    def __init__(self, router):
        self._router = router

    def qsize(self):
        return self._router._gossip_depth()

    def queue_capacity(self):
        return self._router._gossip_capacity()


# -- the router -------------------------------------------------------------


class FleetRouter:
    """The fleet's front door: one HTTP listener fanning out to N
    replicas. ``port=0`` binds an ephemeral port (tests); read it
    back via ``.port`` after ``start()``. Replicas enter via
    :meth:`add_replica` (optionally spawned by :func:`spawn_replica`)
    and leave via :meth:`drain` (graceful, migrates live sessions) or
    :meth:`remove` (immediate)."""

    def __init__(self, host=None, port=None, *, vnodes=None,
                 probe_ms=None, retries=None, timeout_ms=None,
                 drain_timeout_ms=None, canary_fraction=None,
                 shadow_tol=None, canary_threshold=None):
        from .. import env as _env

        self._host = host if host is not None else _env.get_str(
            "MXNET_SERVING_HOST", "127.0.0.1")
        self._port = int(port if port is not None else 0)
        self._probe_s = float(
            probe_ms if probe_ms is not None else
            _env.get_float("MXNET_FLEET_PROBE_MS", 100.0)) / 1e3
        self._retries = int(
            retries if retries is not None else
            _env.get_int("MXNET_FLEET_RETRIES", 2))
        self._timeout_s = float(
            timeout_ms if timeout_ms is not None else
            _env.get_float("MXNET_FLEET_TIMEOUT_MS", 30000.0)) / 1e3
        self._drain_timeout_s = float(
            drain_timeout_ms if drain_timeout_ms is not None else
            _env.get_float("MXNET_FLEET_DRAIN_TIMEOUT_MS",
                           10000.0)) / 1e3
        self._canary_fraction = float(
            canary_fraction if canary_fraction is not None else
            _env.get_float("MXNET_SERVING_CANARY_FRACTION", 0.1))
        self._shadow_tol = float(
            shadow_tol if shadow_tol is not None else
            _env.get_float("MXNET_QUANTIZE_SHADOW_TOL", 0.1))
        # guards: _replicas, _ring, _sessions, _tick, _drain_events,
        # guards: _canary_active
        self._lock = _locks.RankedLock("serving.fleet")
        self._replicas = {}      # name -> Replica
        self._ring = _HashRing(
            vnodes if vnodes is not None else
            _env.get_int("MXNET_FLEET_VNODES", 64))
        self._sessions = {}      # sid -> replica name (affinity pins)
        self._tick = 0           # canary counter-routing clock
        self._drain_events = {}  # name -> Event (set when drain done)
        self._canary_active = True
        self._canary_breaker = CircuitBreaker(
            threshold=(canary_threshold if canary_threshold is not None
                       else _env.get_int(
                           "MXNET_SERVING_CANARY_THRESHOLD", 3)),
            name="fleet.canary")
        self._admission = AdmissionController(_FleetLoad(self))
        self._httpd = None
        self._thread = None
        self._probe_stop = threading.Event()
        self._probe_thread = None
        _ROUTERS.add(self)

    # -- lifecycle -----------------------------------------------------

    def start(self):
        """Bind, serve, and start the gossip probe loop; returns
        self."""
        if self._httpd is not None:
            return self
        router = self

        class _Handler(_FleetHandler):
            fleet = router

        self._httpd = ThreadingHTTPServer((self._host, self._port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="mxnet-fleet-router", daemon=True)
        self._thread.start()
        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="mxnet-fleet-probe",
            daemon=True)
        self._probe_thread.start()
        return self

    @property
    def port(self):
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._port

    @property
    def address(self):
        return f"http://{self._host}:{self.port}"

    def stop(self, stop_replicas=False):
        """Stop probing and listening. Replica processes the router
        spawned are stopped only with ``stop_replicas=True`` — by
        default the caller owns them."""
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join()
            self._probe_thread = None
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._admission.close()
        if stop_replicas:
            with self._lock:
                procs = [r.process for r in self._replicas.values()
                         if r.process is not None]
            for proc in procs:
                proc.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- membership ----------------------------------------------------

    def add_replica(self, name, url, canary=False, process=None,
                    wait_warm=True, timeout_s=120.0):
        """Join ``url`` to the fleet as ``name``. With ``wait_warm``
        (default) the call blocks until the replica's ``/healthz``
        answers 200+warm — a cold replica never enters the ring, so
        clients never eat its compiles."""
        rep = Replica(name, url, canary=canary, process=process)
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already in fleet")
            self._replicas[name] = rep  # joining: visible, unrouted
        if wait_warm:
            try:
                self._wait_warm(rep, timeout_s)
            except BaseException:
                with self._lock:
                    self._replicas.pop(name, None)
                raise
        with self._lock:
            rep.state = "serving"
            self._ring.add(name)
        _FLEET.add("joins")
        return rep

    def _wait_warm(self, rep, timeout_s):
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                status, doc = self._http_health(rep)
            except _TransportError:
                status, doc = None, {}
            if status == 200 and doc.get("warm"):
                with self._lock:
                    rep.health = doc
                    rep.warm = True
                    rep.depth = int(doc.get("queue_depth", 0) or 0)
                    rep.capacity = max(
                        int(doc.get("queue_capacity", 1) or 1), 1)
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica {rep.name!r} at {rep.url} did not warm "
                    f"within {timeout_s:.0f}s (last status {status})")
            time.sleep(0.05)

    def remove(self, name):
        """Immediate removal (no migration — use :meth:`drain` for
        graceful). Pinned sessions re-pin by ring on their next step
        (their server-side state is gone: the stream restarts)."""
        with self._lock:
            rep = self._replicas.pop(name, None)
            if rep is None:
                return None
            self._ring.remove(name)
            rep.state = "left"
            ev = self._drain_events.pop(name, None)
        if ev is not None:
            ev.set()
        return rep

    def replicas(self):
        with self._lock:
            return {n: r.snapshot() for n, r in self._replicas.items()}

    # -- gossip / probe loop -------------------------------------------

    def _probe_loop(self):
        while not self._probe_stop.wait(self._probe_s):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — probe loop must survive
                logging.exception("fleet: probe loop error")

    def probe_once(self):
        """One gossip round: GET every replica's ``/healthz``; update
        depth/warm, feed the per-replica breaker, eject on open,
        recover on a successful probe. Public so tests drive gossip
        deterministically without the timer."""
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if r.state in ("joining", "serving", "draining",
                                   "ejected")]
        for rep in reps:
            _FLEET.add("probes")
            try:
                status, doc = self._http_health(rep)
            except _TransportError:
                rep.breaker.record_failure()
                with self._lock:
                    if rep.state == "serving" and \
                            rep.breaker.state != "closed":
                        self._eject_locked(rep)
                continue
            # any HTTP answer (200 warm, 503 warming) is a live
            # process: reset the breaker
            rep.breaker.record_success()
            with self._lock:
                rep.health = doc
                rep.warm = bool(doc.get("warm"))
                rep.depth = int(doc.get("queue_depth", 0) or 0)
                rep.capacity = max(
                    int(doc.get("queue_capacity",
                                rep.capacity) or 1), 1)
                if rep.state == "ejected":
                    rep.state = "serving"
                    self._ring.add(rep.name)
                    _FLEET.add("recoveries")
                    logging.warning("fleet: replica %s recovered",
                                    rep.name)

    def _eject_locked(self, rep):
        rep.state = "ejected"
        self._ring.remove(rep.name)
        _FLEET.add("ejections")
        logging.warning(
            "fleet: ejected replica %s (probe breaker %s)",
            rep.name, rep.breaker.state)

    def _gossip_depth(self):
        with self._lock:
            return sum(r.depth for r in self._replicas.values()
                       if r.state == "serving")

    def _gossip_capacity(self):
        with self._lock:
            caps = [r.capacity for r in self._replicas.values()
                    if r.state == "serving"]
        return sum(caps) if caps else 1

    # -- drain (graceful leave with live-session migration) ------------

    def drain(self, name, timeout_s=None):
        """Gracefully remove ``name``: stop routing new work to it
        (requests for its pinned sessions PARK at the router), wait
        for its queue to empty, export its live decode state, restore
        each session onto its ring successor (dense-row form — the
        peer may run a different page geometry), re-pin, release the
        parked requests, and drop the replica. Returns the number of
        sessions migrated. On any failure the replica is restored to
        serving — its state never left it, so nothing is lost."""
        timeout = timeout_s if timeout_s is not None else \
            self._drain_timeout_s
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError(f"no replica {name!r} in fleet")
            if rep.state != "serving":
                raise ValueError(
                    f"replica {name!r} is {rep.state}, not serving")
            rep.state = "draining"
            self._ring.remove(name)
            ev = self._drain_events[name] = threading.Event()
        _FLEET.add("drains")
        try:
            moved = self._migrate(rep, timeout)
        except BaseException:
            with self._lock:
                rep.state = "serving"
                self._ring.add(name)
                self._drain_events.pop(name, None)
            ev.set()  # parked requests resume against the same pin
            raise
        with self._lock:
            rep.state = "left"
            self._replicas.pop(name, None)
            self._drain_events.pop(name, None)
        ev.set()
        logging.info("fleet: drained replica %s (%d sessions moved)",
                     name, moved)
        return moved

    def _migrate(self, rep, timeout):
        deadline = time.monotonic() + timeout
        # 1) the router is the only ingress, so once marked draining
        # no new work arrives; wait for in-flight work to finish
        while True:
            try:
                status, doc = self._http_health(rep)
            except _TransportError as e:
                raise RuntimeError(
                    f"drain: replica {rep.name} unreachable: {e}") \
                    from e
            if int(doc.get("queue_depth", 0) or 0) == 0:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"drain: replica {rep.name} queue did not empty "
                    f"within {timeout:.1f}s")
            time.sleep(0.02)
        # 2) export the live decode state
        status, _, _, body = self._forward(
            rep, "GET", "/admin/export_state", None, {})
        if status == 409:
            return 0  # stateless replica: nothing to migrate
        if status != 200:
            raise RuntimeError(
                f"drain: export_state on {rep.name} answered {status}")
        payload = pickle.loads(body)
        sessions = payload.get("sessions", {})
        if not sessions:
            return 0
        # 3) partition by ring successor (the ring already excludes
        # the drainee) and restore each shard onto its new home
        with self._lock:
            shards = {}
            for sid in sessions:
                tname = self._ring.lookup(sid)
                target = self._replicas.get(tname) \
                    if tname is not None else None
                if target is None or target.state != "serving":
                    raise RuntimeError(
                        "drain: no serving peer to migrate live "
                        "sessions to")
                shards.setdefault(tname, []).append(sid)
            targets = {n: self._replicas[n] for n in shards}
        moved = 0
        for tname, sids in shards.items():
            sub = {"format": payload.get("format", 1),
                   "state_shapes": payload.get("state_shapes"),
                   "state_dtypes": payload.get("state_dtypes"),
                   "sessions": {sid: sessions[sid] for sid in sids}}
            data = pickle.dumps(sub,
                                protocol=pickle.HIGHEST_PROTOCOL)
            status, _, _, rbody = self._forward(
                targets[tname], "POST", "/admin/restore_state", data,
                {"Content-Type": "application/octet-stream"})
            if status != 200:
                raise RuntimeError(
                    f"drain: restore_state on {tname} answered "
                    f"{status}: {rbody[:200]!r}")
            moved += int(json.loads(rbody).get("restored", 0))
            with self._lock:
                for sid in sids:
                    self._sessions[sid] = tname
                    _FLEET.add("affinity_moves")
        _FLEET.add("drained_sessions", moved)
        return moved

    # -- request routing -----------------------------------------------

    def forward_request(self, path, body, slo_class, session_id,
                        headers):
        """Route one client POST. Raises
        :class:`~mxnet_tpu.serving.admission.ShedLoad` (handler maps
        to 503 + Retry-After); otherwise returns the replica reply as
        ``(status, content_type, extra_headers, body)``."""
        _FLEET.add("requests")
        self._admission.check(slo_class)
        if session_id is not None:
            return self._route_stateful(path, body, headers,
                                        session_id)
        return self._route_stateless(path, body, headers, slo_class)

    def _route_stateful(self, path, body, headers, sid):
        """Affinity routing: the stream's state lives on exactly one
        replica. No cross-replica retry — a transport failure is a
        503 (the probe loop will eject the replica; the client
        restarts its stream, which then re-pins by ring)."""
        deadline = time.monotonic() + self._drain_timeout_s
        while True:
            ev = None
            target = None
            with self._lock:
                pinned = self._sessions.get(sid)
                rep = self._replicas.get(pinned) \
                    if pinned is not None else None
                if rep is not None and rep.state == "serving":
                    target = rep
                elif rep is not None and rep.state == "draining":
                    ev = self._drain_events.get(pinned)
                if target is None and ev is None:
                    # unpinned, or the pinned replica is gone: (re-)
                    # place by ring
                    tname = self._ring.lookup(sid)
                    cand = self._replicas.get(tname) \
                        if tname is not None else None
                    if cand is not None and cand.state == "serving":
                        if pinned is not None and pinned != tname:
                            _FLEET.add("affinity_moves")
                        self._sessions[sid] = tname
                        target = cand
                if target is not None:
                    target.requests += 1
            if target is not None:
                try:
                    reply = self._forward(target, "POST", path, body,
                                          headers)
                except _TransportError as e:
                    _FLEET.add("transport_errors")
                    target.breaker.record_failure()
                    return (503, "application/json", {},
                            json.dumps({
                                "error": f"replica {target.name} "
                                         f"unreachable: {e}",
                                "request_id": headers.get(
                                    "X-Request-Id"),
                                "retry_after_s": 0.1}).encode())
                target.breaker.record_success()
                _FLEET.add("routed")
                return reply
            if ev is not None:
                # the stream's home is mid-drain: park until its
                # state lands on the successor, then re-resolve
                _FLEET.add("blocked_on_drain")
                if not ev.wait(max(deadline - time.monotonic(), 0.0)):
                    _FLEET.add("drain_timeouts")
                    return (503, "application/json", {},
                            json.dumps({
                                "error": "session home is draining; "
                                         "retry",
                                "request_id": headers.get(
                                    "X-Request-Id"),
                                "retry_after_s": 0.1}).encode())
                continue
            _FLEET.add("no_replica")
            return (503, "application/json", {},
                    json.dumps({
                        "error": "no serving replica in fleet",
                        "request_id": headers.get("X-Request-Id"),
                        "retry_after_s": 0.5}).encode())

    def _route_stateless(self, path, body, headers, slo_class):
        """Least-loaded routing with bounded cross-replica retry on
        transport failure, plus canary counter-routing."""
        canary_rep = None
        if slo_class != "critical":
            with self._lock:
                if self._canary_active and self._canary_fraction > 0:
                    canaries = [r for r in self._replicas.values()
                                if r.canary and r.state == "serving"]
                    if canaries:
                        # deterministic counter routing (round 19):
                        # exactly fraction f of ticks flip the bucket
                        self._tick += 1
                        f = min(self._canary_fraction, 1.0)
                        if int(self._tick * f) != \
                                int((self._tick - 1) * f):
                            canary_rep = min(
                                canaries,
                                key=lambda r: (r.depth, r.name))
        excluded = set()
        for attempt in range(self._retries + 1):
            with self._lock:
                pool = [r for r in self._replicas.values()
                        if r.state == "serving" and not r.canary and
                        r.name not in excluded]
                if not pool:  # canary-only fleet: better than a 503
                    pool = [r for r in self._replicas.values()
                            if r.state == "serving" and
                            r.name not in excluded]
                rep = min(pool, key=lambda r: (r.depth, r.name)) \
                    if pool else None
                if rep is not None:
                    rep.requests += 1
            if rep is None:
                _FLEET.add("no_replica")
                return (503, "application/json", {},
                        json.dumps({
                            "error": "no serving replica in fleet",
                            "request_id": headers.get("X-Request-Id"),
                            "retry_after_s": 0.5}).encode())
            try:
                reply = self._forward(rep, "POST", path, body, headers)
            except _TransportError:
                _FLEET.add("transport_errors")
                rep.breaker.record_failure()
                excluded.add(rep.name)
                if attempt < self._retries:
                    _FLEET.add("retries")
                    continue
                return (503, "application/json", {},
                        json.dumps({
                            "error": "all fleet replicas unreachable",
                            "request_id": headers.get("X-Request-Id"),
                            "retry_after_s": 0.5}).encode())
            rep.breaker.record_success()
            if canary_rep is not None and canary_rep.name != rep.name:
                reply = self._shadow_canary(canary_rep, reply, path,
                                            body, headers)
            _FLEET.add("routed")
            return reply
        raise AssertionError("unreachable")  # pragma: no cover

    def _shadow_canary(self, canary, incumbent_reply, path, body,
                       headers):
        """Shadow-pair canary: the canary answers only when it agrees
        with the incumbent (round-19 accuracy gate). Every failure
        mode — transport, 5xx, shadow mismatch — falls back to the
        incumbent reply, so the client NEVER sees a canary fault."""
        _FLEET.add("canary_requests")
        with self._lock:
            canary.requests += 1
        try:
            creply = self._forward(canary, "POST", path, body, headers)
        except _TransportError:
            _FLEET.add("canary_fallbacks")
            self._canary_failure("transport error")
            return incumbent_reply
        cstatus, _, _, cbody = creply
        istatus, _, _, ibody = incumbent_reply
        if cstatus != 200:
            _FLEET.add("canary_fallbacks")
            if cstatus >= 500:
                self._canary_failure(f"HTTP {cstatus}")
            return incumbent_reply
        if istatus != 200:
            # the incumbent itself failed (shed/backpressure): that IS
            # the fleet's answer — nothing to compare against
            return incumbent_reply
        _FLEET.add("shadow_checks")
        try:
            dev = _rel_deviation(json.loads(cbody).get("outputs"),
                                 json.loads(ibody).get("outputs"))
        except Exception:  # noqa: BLE001 — malformed reply == mismatch
            dev = float("inf")
        if dev > self._shadow_tol:
            _FLEET.add("shadow_mismatches")
            _FLEET.add("canary_fallbacks")
            self._canary_failure(f"shadow deviation {dev:.4g}")
            return incumbent_reply
        self._canary_breaker.record_success()
        return creply

    def _canary_failure(self, why):
        self._canary_breaker.record_failure()
        rolled = False
        with self._lock:
            if self._canary_active and \
                    self._canary_breaker.state != "closed":
                self._canary_active = False
                rolled = True
        if rolled:
            _FLEET.add("canary_rollbacks")
            logging.warning(
                "fleet: canary rolled back (%s); all traffic to "
                "incumbents", why)

    @property
    def canary_active(self):
        with self._lock:
            return self._canary_active

    # -- HTTP plumbing (never under the lock) --------------------------

    def _forward(self, rep, method, path, body, headers):
        """One replica call. HTTP error statuses are ROUTED replies
        (returned); connection failures raise
        :class:`_TransportError`."""
        req = urllib.request.Request(rep.url + path, data=body,
                                     headers=dict(headers),
                                     method=method)
        try:
            with urllib.request.urlopen(
                    req, timeout=self._timeout_s) as resp:
                return (resp.status,
                        resp.headers.get("Content-Type",
                                         "application/json"),
                        self._passthrough(resp.headers), resp.read())
        except urllib.error.HTTPError as e:
            data = e.read()
            return (e.code,
                    e.headers.get("Content-Type", "application/json"),
                    self._passthrough(e.headers), data)
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise _TransportError(f"{rep.name}: {e}") from e

    @staticmethod
    def _passthrough(hdrs):
        out = {}
        ra = hdrs.get("Retry-After")
        if ra is not None:
            out["Retry-After"] = ra
        return out

    def _http_health(self, rep):
        try:
            with urllib.request.urlopen(
                    rep.url + "/healthz",
                    timeout=self._timeout_s) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                doc = json.loads(e.read() or b"{}")
            except ValueError:
                doc = {}
            return e.code, doc
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise _TransportError(f"{rep.name}: {e}") from e

    # -- observability -------------------------------------------------

    def healthz(self):
        """The router's own /healthz document: per-replica states, the
        aggregate queue picture, and the fleet-wide SLO block."""
        slo = self._admission.snapshot()
        with self._lock:
            reps = {n: r.snapshot()
                    for n, r in self._replicas.items()}
            sessions = len(self._sessions)
            canary_active = self._canary_active
        serving = [r for r in reps.values() if r["state"] == "serving"]
        warm = bool(serving) and all(r["warm"] for r in serving)
        status = "ok" if warm else "warming"
        if warm and any(r["state"] in ("ejected", "draining")
                        for r in reps.values()):
            status = "degraded"
        return {"status": status, "warm": warm, "role": "router",
                "replicas": reps, "sessions": sessions,
                "canary_active": canary_active,
                "queue_depth": sum(r["queue_depth"] for r in serving),
                "queue_capacity": (sum(r["queue_capacity"]
                                       for r in serving)
                                   if serving else 1),
                "slo": slo}

    def _replica_rows(self):
        with self._lock:
            return [(r.name, r.state, r.warm, r.depth, r.requests,
                     r.canary) for r in self._replicas.values()]


# -- prometheus exposition --------------------------------------------------


def _render_fleet():
    """The ``fleet`` exposition block: flat router counters (this
    block REPLACES the family's gauge pass, so they must render here)
    plus per-replica labeled series across live routers."""
    lines = ["# HELP mxnet_fleet fleet router counters",
             "# TYPE mxnet_fleet gauge"]
    snap = _FLEET.snapshot()
    for key in sorted(snap):
        lines.append(f"mxnet_fleet_{key} {snap[key]}")
    up, depth, reqs, states = [], [], [], []
    for router in list(_ROUTERS):
        for name, state, warm, d, n, canary in router._replica_rows():
            lab = {"replica": name}
            up.append((lab, 1 if state == "serving" else 0))
            depth.append((lab, d))
            reqs.append((lab, n))
            states.append(({"replica": name, "state": state,
                            "canary": "true" if canary else "false"},
                           1))
    lines += _tmetrics.labeled_lines(
        "fleet_replica_up", up, "replica serving and in the ring")
    lines += _tmetrics.labeled_lines(
        "fleet_replica_queue_depth", depth,
        "last gossiped replica queue depth")
    lines += _tmetrics.labeled_lines(
        "fleet_replica_requests", reqs,
        "requests routed to this replica")
    lines += _tmetrics.labeled_lines(
        "fleet_replica_state", states, "replica lifecycle state")
    return "\n".join(lines)


_tmetrics.register_exposition("fleet", _render_fleet)


# -- the router's HTTP handler ----------------------------------------------


class _FleetHandler(BaseHTTPRequestHandler):
    fleet = None  # bound per-router by FleetRouter.start
    protocol_version = "HTTP/1.1"
    _request_id = None
    _status = None

    def log_message(self, fmt, *args):
        logging.debug("fleet http: " + fmt, *args)

    def _reply(self, code, body, content_type="application/json",
               headers=None):
        if isinstance(body, (dict, list)):
            body = json.dumps(body).encode()
        self._status = code
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if self._request_id is not None:
            self.send_header("X-Request-Id", self._request_id)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code, message, retry_after_s=None):
        doc = {"error": message, "request_id": self._request_id,
               "retry_after_s": retry_after_s}
        headers = {}
        if retry_after_s is not None:
            headers["Retry-After"] = f"{max(retry_after_s, 0.0):.3f}"
        self._reply(code, doc, headers=headers)

    def do_GET(self):
        fr = self.fleet
        if self.path == "/healthz":
            doc = fr.healthz()
            self._reply(200 if doc["warm"] else 503, doc)
        elif self.path == "/fleet":
            # the operator view: same document, always 200 (asking
            # "who is in the fleet" must work while warming)
            self._reply(200, fr.healthz())
        elif self.path == "/metrics":
            self._reply(200, _tmetrics.prometheus_text().encode(),
                        content_type="text/plain; version=0.0.4")
        else:
            self._error(404, f"no route {self.path!r}")

    def do_POST(self):
        self._request_id = (self.headers.get("X-Request-Id") or
                            _telem.new_trace_id())
        with _telem.trace_context(self._request_id):
            with _telem.span("fleet.request", cat="serving",
                             path=self.path) as sp:
                self._do_post()
                sp.set(status=self._status)

    def _do_post(self):
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            self._error(400, "bad Content-Length")
            return
        if length <= 0 or length > _MAX_BODY:
            self._error(400, f"body length {length} out of bounds "
                             f"(max {_MAX_BODY})")
            return
        body = self.rfile.read(length)
        ctype = (self.headers.get("Content-Type") or
                 "application/json").split(";")[0].strip().lower()
        slo_class = self.headers.get("X-SLO-Class")
        timeout_ms = self.headers.get("X-Timeout-Ms")
        session_id = self.headers.get("X-Session-Id")
        if ctype == "application/json":
            # peek at the body for routing keys (body wins, like the
            # replica surface); an unparseable body still routes —
            # the replica answers the canonical 400 envelope
            try:
                doc = json.loads(body)
                if isinstance(doc, dict):
                    slo_class = doc.get("slo_class", slo_class)
                    timeout_ms = doc.get("timeout_ms", timeout_ms)
                    session_id = doc.get("session_id", session_id)
            except ValueError:
                pass
        try:
            slo_class = normalize_class(slo_class)
        except ValueError as e:
            self._error(400, str(e))
            return
        headers = {"Content-Type": self.headers.get("Content-Type") or
                   "application/json",
                   "X-SLO-Class": slo_class,
                   "X-Request-Id": self._request_id}
        if timeout_ms is not None:
            headers["X-Timeout-Ms"] = str(timeout_ms)
        if session_id is not None:
            headers["X-Session-Id"] = str(session_id)
        fr = self.fleet
        try:
            status, rctype, extra, rbody = fr.forward_request(
                self.path, body, slo_class,
                str(session_id) if session_id is not None else None,
                headers)
        except ShedLoad as e:
            _FLEET.add("shed")
            self._error(503, str(e),
                        retry_after_s=max(e.retry_after_s, 0.0))
            return
        except Exception as e:  # noqa: BLE001 — HTTP boundary
            logging.exception("fleet: routing failed")
            self._error(500, f"{type(e).__name__}: {e}")
            return
        self._reply(status, rbody, content_type=rctype,
                    headers=extra)


# -- replica subprocess helpers ---------------------------------------------

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

_CHILD_BOOT = (
    "import sys; sys.path.insert(0, {root!r})\n"
    "from _cpu_platform import force_cpu_platform\n"
    "force_cpu_platform()\n"
    "from mxnet_tpu.serving.fleet import _replica_child\n"
    "_replica_child({factory!r}, {bundle!r})\n")


class ReplicaProcess:
    """Handle on a replica subprocess from :func:`spawn_replica`:
    the base URL, the ready document the child printed (``warm`` =
    its ``warmup()`` stats — ``compiles == 0`` proves a bundle-warm
    join never compiled), and a graceful ``stop()`` (close the
    child's stdin; it shuts its server down and exits)."""

    def __init__(self, proc, url, port, ready):
        self.proc = proc
        self.url = url
        self.port = port
        self.ready = ready

    @property
    def alive(self):
        return self.proc.poll() is None

    def stop(self, timeout_s=30.0):
        if self.proc.poll() is None:
            try:
                self.proc.stdin.close()
            except OSError:
                pass
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()

    def kill(self):
        """Hard kill — the fleet tests' stand-in for a crashed
        replica (probe ejection drills)."""
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()


def spawn_replica(factory, bundle=None, env=None, timeout_s=300.0):
    """Start one replica subprocess serving ``factory`` — a
    ``"module:function"`` returning a built
    :class:`~mxnet_tpu.serving.session.InferenceSession`. With
    ``bundle=`` the child imports the compiled-artifact bundle before
    ``warmup()`` (round 20), so combined with a shared
    ``MXNET_COMPILE_CACHE_DIR``/``MXNET_ARTIFACT_REMOTE`` in ``env``
    the join is compile-free. Blocks until the child prints its ready
    line; returns a :class:`ReplicaProcess`."""
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    # compiles at dispatch time land in the shared store immediately,
    # so a peer joining later warms from them (round 23 satellite)
    child_env.setdefault("MXNET_DISPATCH_EAGER_PERSIST", "1")
    child_env.update(env or {})
    code = _CHILD_BOOT.format(root=_REPO_ROOT, factory=factory,
                              bundle=bundle)
    proc = subprocess.Popen(
        [sys.executable, "-c", code], env=child_env, cwd=_REPO_ROOT,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    got = {}

    def _read():
        got["line"] = proc.stdout.readline()

    reader = threading.Thread(target=_read, daemon=True)
    reader.start()
    reader.join(timeout_s)
    line = got.get("line")
    if not line:
        proc.kill()
        try:
            err = proc.stderr.read()
        except Exception:  # noqa: BLE001 — already failing
            err = ""
        raise RuntimeError(
            "replica child did not become ready within "
            f"{timeout_s:.0f}s: {err[-2000:]}")
    ready = json.loads(line)
    port = int(ready["port"])
    return ReplicaProcess(proc, f"http://127.0.0.1:{port}", port,
                          ready)


def _replica_child(factory, bundle=None):
    """Subprocess entry point (see :data:`_CHILD_BOOT`): import the
    bundle, build the session via ``factory``, warm it, serve on an
    ephemeral port, print ONE json ready line, then block until the
    parent closes stdin."""
    import importlib

    from .. import artifact as _artifact
    from ..utils import compile_cache as _cc
    from .server import ModelServer

    if bundle:
        _artifact.import_bundle(bundle)
    mod, _, fn = factory.partition(":")
    session = getattr(importlib.import_module(mod), fn)()
    # count the SERVING path only: construction dispatches one-shot
    # eager ops; the ready line's compile stats gate the zero-compile
    # join promise on warmup + first traffic
    _cc.reset_compile_cache_counters()
    warm = session.warmup()
    srv = ModelServer(session=session, port=0).start()
    sys.stdout.write(json.dumps({
        "port": srv.port, "warm": warm,
        "compile": _tmetrics.family_snapshot("compile_cache")}) + "\n")
    sys.stdout.flush()
    try:
        sys.stdin.read()  # parent closes stdin to stop us
    except KeyboardInterrupt:  # pragma: no cover
        pass
    srv.stop()
