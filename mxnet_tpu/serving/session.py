"""InferenceSession: an exported/hybridizable Block as a serving engine.

Turns a model — a hybridizable ``gluon.Block``, or an exported
``*-symbol.json`` + ``*.params`` pair via :meth:`InferenceSession.load`
(reference analog: the MXNet model-server loading ``SymbolBlock.imports``
artifacts) — into a fixed set of **bucket executables**: one AOT-compiled
XLA program per configured batch size. Requests of any batch size are
padded up to the smallest covering bucket and outputs sliced back, the
``MXNET_SHAPE_BUCKETS`` discipline (round 9) applied to whole-model
inference, so a variable request stream never retraces.

Eval-mode contract: forward runs under ``autograd.pause
(train_mode=False)`` — no tape, no BatchNorm stat updates, dropout off —
and parameter mutation during the trace is dropped with a one-time
warning (a serving forward must be side-effect free). Outputs must be
batch-major and row-independent (output row i depends on input row i
only), which every standard inference head satisfies; padding is
zero-fill and padded rows are sliced off before anyone reads them.

Warm start: each bucket executable is resolved through the persistent
compile cache (``utils/compile_cache.py``) under a fingerprint of the
model's symbol-graph JSON + parameter/input avals + AMP version. A warm
process deserializes every bucket at :meth:`warmup` — **zero traces,
zero XLA compiles** before the first request, verifiable via
``profiler.compile_cache_counters()['retraces']``. Models that cannot
symbol-trace fall back to memory-only executables (first process pays
the compile; correctness unchanged).

Round 16 — stateful incremental decode: a session constructed with
``state_shapes=`` compiles a **step executable** instead, the pure
function ``(params, key, inputs, states) -> (outputs, new_states)``
with the state arguments DONATED (state-in/state-out at zero copies)
and bucketed on **batch occupancy** — how many live sequences ride
this step — so one AOT program serves any batch membership of the
continuous batcher. Step executables are fingerprinted with a
state-shape salt (kind ``serving_step``), so stateless and stateful
artifacts of the same graph never collide on disk. The block contract:
``forward(*inputs, *states)`` returns the flat tuple
``(*outputs, *new_states)`` — exactly what ``RecurrentCell``-style
cells emit. :meth:`step` is the single-process API;
``DynamicBatcher`` drives :meth:`_run_step` directly with slots
gathered from the session's :class:`~.state.SessionStateStore`.
"""
from __future__ import annotations

import hashlib
import logging
import threading
import time

import numpy as onp

from .. import autograd
from .. import ndarray as nd
from ..base import MXNetError
from ..ndarray import NDArray
from .. import random as mxrandom
from ..artifact import CompiledArtifact
from ..utils import compile_cache as cc
from ..utils import locks as _locks
from .metrics import METRICS

__all__ = ["InferenceSession", "parse_buckets"]


def parse_buckets(raw, max_batch):
    """Batch-size buckets from an ``MXNET_SERVING_BUCKETS``-style spec:
    ``pow2`` (default) — powers of two up to ``max_batch``; ``mult:N`` —
    multiples of N up to ``max_batch``; or an explicit comma list
    ("1,4,16,64"). Always includes ``max_batch`` itself and is returned
    sorted ascending."""
    raw = (raw or "pow2").strip()
    buckets = set()
    if raw == "pow2":
        b = 1
        while b < max_batch:
            buckets.add(b)
            b <<= 1
    elif raw.startswith("mult:"):
        try:
            n = int(raw.split(":", 1)[1])
        except ValueError:
            n = 0
        if n < 1:
            raise MXNetError(
                f"invalid bucket spec {raw!r} (expected mult:N, N >= 1)")
        buckets.update(range(n, max_batch, n))
    else:
        try:
            buckets.update(int(tok) for tok in raw.split(",") if tok.strip())
        except ValueError:
            raise MXNetError(
                f"invalid bucket spec {raw!r} (expected pow2 | mult:N | "
                "comma list)") from None
        if any(b < 1 for b in buckets):
            raise MXNetError(f"bucket sizes must be >= 1 (got {raw!r})")
        # explicit lists fail fast instead of silently dropping
        # entries the operator configured (generated specs cap quietly)
        too_big = sorted(b for b in buckets if b > max_batch)
        if too_big:
            raise MXNetError(
                f"explicit bucket(s) {too_big} exceed max_batch "
                f"{max_batch}; raise MXNET_SERVING_MAX_BATCH or drop "
                "them")
    buckets.add(int(max_batch))
    return sorted(b for b in buckets if b <= max_batch)


class _InputSpec:
    """One data input: name + per-row (batch-less) shape + dtype."""

    __slots__ = ("name", "row_shape", "dtype")

    def __init__(self, name, row_shape, dtype):
        self.name = name
        self.row_shape = tuple(int(d) for d in row_shape)
        self.dtype = onp.dtype(dtype)

    def __repr__(self):
        return (f"_InputSpec({self.name!r}, (N, "
                f"{', '.join(map(str, self.row_shape))}), {self.dtype})")


class _BucketEntry:
    """One resolved bucket: the executable + its provenance."""

    __slots__ = ("bucket", "amp_ver", "fn", "num_outputs", "from_disk")

    def __init__(self, bucket, amp_ver, fn, num_outputs, from_disk):
        self.bucket = bucket
        self.amp_ver = amp_ver
        self.fn = fn
        self.num_outputs = num_outputs
        self.from_disk = from_disk


class InferenceSession:
    """Eval-mode, no-tape, bucket-compiled forward over a Block.

    Parameters
    ----------
    block : gluon.Block
        The model. Parameters must be initialized, or initializable
        from one eager forward over a zeros example.
    example : NDArray / numpy array / tuple of them, optional
        Example input(s) — batch axis first — from which per-input row
        shapes and dtypes are taken. Exactly one of ``example`` /
        ``input_shapes`` is required.
    input_shapes : sequence of shape tuples, optional
        Full input shapes INCLUDING a (placeholder) batch axis, e.g.
        ``[(1, 784)]``; dtype float32 unless ``input_dtypes`` is given.
    input_dtypes : sequence of dtypes, optional
    buckets : sequence of int, optional
        Batch-size buckets to compile. Default: the
        ``MXNET_SERVING_BUCKETS`` policy over ``MXNET_SERVING_MAX_BATCH``.
    max_batch : int, optional
        Upper bucket bound (default ``MXNET_SERVING_MAX_BATCH``).
        Larger requests are chunked.
    warm : bool
        Resolve every bucket executable in the constructor (AOT compile
        or disk deserialize). ``warm=False`` defers each bucket to its
        first request.
    state_shapes : sequence of shape tuples, optional
        Per-state ROW shapes (no batch axis) the block threads —
        ``RecurrentCell.state_row_shapes()`` emits them. Makes the
        session STATEFUL: it compiles occupancy-bucketed step
        executables and owns a :class:`~.state.SessionStateStore`
        (see :meth:`step`); :meth:`predict` is disabled.
    state_dtypes : sequence of dtypes, optional (default float32)
    state_store : SessionStateStore, optional
        Share an existing store instead of constructing one (canary
        versions of one model each get their own by default).
    """

    def __init__(self, block, example=None, input_shapes=None,
                 input_dtypes=None, buckets=None, max_batch=None,
                 warm=True, label=None, state_shapes=None,
                 state_dtypes=None, state_store=None):
        from .. import env as _env

        self._block = block
        # display label for breaker names / repository healthz (the
        # ModelRepository passes "name@vN" so operators can tell WHICH
        # model's bucket degraded)
        self.label = label
        # guards: _entries, _breakers, _demoted, _artifact_fps, _num_outputs
        self._lock = _locks.RankedLock("serving.session")
        self._entries = {}  # (bucket, amp_ver) -> _BucketEntry
        self._breakers = {}  # (bucket, amp_ver) -> CircuitBreaker
        self._demoted = set()  # (bucket, amp_ver) forced to the jit path
        self._artifact_fps = set()  # fingerprints resolved this process
        self._num_outputs = None
        self._mutation_warned = False
        max_batch = int(max_batch or _env.get_int(
            "MXNET_SERVING_MAX_BATCH", 32))
        if buckets is None:
            buckets = parse_buckets(
                _env.get_str("MXNET_SERVING_BUCKETS"), max_batch)
        self.buckets = sorted(int(b) for b in set(buckets))
        if not self.buckets or self.buckets[0] < 1:
            raise MXNetError("buckets must be a non-empty set of "
                             f"positive batch sizes (got {buckets})")
        self._input_specs = self._resolve_input_specs(
            example, input_shapes, input_dtypes)
        self._state_specs = []
        self.state_store = None
        self._owns_store = False
        self._step_entries = {}  # (occupancy, amp_ver) -> _BucketEntry
        self._step_jitted_by_ver = {}
        if state_store is not None or state_shapes is not None:
            from .state import SessionStateStore

            if state_store is not None:
                self.state_store = state_store
                state_shapes = state_store.state_shapes
                if state_dtypes is None:
                    state_dtypes = [str(dt)
                                    for dt in state_store.state_dtypes]
            dts = state_dtypes or ["float32"] * len(state_shapes)
            self._state_specs = [
                _InputSpec(f"state{i}", s, dt)
                for i, (s, dt) in enumerate(zip(state_shapes, dts))]
            if self.state_store is None:
                # blocks that declare KV-cache rows (state_row_pageable)
                # opt those rows into paged storage — active only when
                # MXNET_SERVING_STATE_PAGE_TOKENS is set
                pageable = None
                proto = getattr(block, "state_row_pageable", None)
                if callable(proto):
                    flags = list(proto())
                    if len(flags) == len(state_shapes):
                        pageable = flags
                self.state_store = SessionStateStore(
                    state_shapes, dts, pageable=pageable, label=label)
                self._owns_store = True
        self._ensure_initialized()
        self._param_list = [p for _, p in
                            sorted(block.collect_params().items())]
        self._param_names = [name for name, _ in
                             sorted(block.collect_params().items())]
        self._param_vals = [p._ndarray._data for p in self._param_list]
        self._graph_sig = self._graph_signature()
        self._jitted_by_ver = {}
        self._shard = None  # set by shard_params(): tensor-parallel mode
        if warm:
            self.warmup()

    # -- construction helpers -----------------------------------------

    @classmethod
    def load(cls, prefix, input_names=None, epoch=0, input_shapes=None,
             **kwargs):
        """Build a session from an exported model: ``{prefix}-symbol.json``
        + ``{prefix}-{epoch:04d}.params`` (the ``Block.export`` layout).
        ``input_names=None`` infers the data inputs as the graph
        variables not present in the params file (SymbolBlock.imports
        loader glue)."""
        import os

        from ..gluon.block import SymbolBlock

        symbol_file = f"{prefix}-symbol.json"
        param_file = f"{prefix}-{epoch:04d}.params"
        if not os.path.exists(param_file):
            # a session over uninitialized params can only serve
            # garbage or die later with a cryptic deferred-init error —
            # name the operator's actual mistake (prefix/epoch) here
            raise MXNetError(
                f"params file {param_file!r} not found (Block.export "
                "writes {prefix}-{epoch:04d}.params; check prefix and "
                "epoch)")
        block = SymbolBlock.imports(symbol_file, input_names, param_file)
        return cls(block, input_shapes=input_shapes, **kwargs)

    def _resolve_input_specs(self, example, input_shapes, input_dtypes):
        if (example is None) == (input_shapes is None):
            raise MXNetError("exactly one of example= / input_shapes= "
                             "is required")
        names = [getattr(i, "name", f"data{k}") for k, i in
                 enumerate(getattr(self._block, "_inputs", []))] or None
        specs = []
        if example is not None:
            if not isinstance(example, (list, tuple)):
                example = [example]
            for k, ex in enumerate(example):
                arr = ex.asnumpy() if isinstance(ex, NDArray) else \
                    onp.asarray(ex)
                if arr.ndim < 1:
                    raise MXNetError("example inputs must carry a batch "
                                     "axis")
                name = names[k] if names and k < len(names) else f"data{k}"
                specs.append(_InputSpec(name, arr.shape[1:], arr.dtype))
        else:
            input_dtypes = input_dtypes or ["float32"] * len(input_shapes)
            for k, (shape, dt) in enumerate(zip(input_shapes,
                                                input_dtypes)):
                if len(shape) < 1:
                    raise MXNetError("input_shapes entries must include "
                                     "the batch axis")
                name = names[k] if names and k < len(names) else f"data{k}"
                specs.append(_InputSpec(name, tuple(shape)[1:], dt))
        return specs

    def _ensure_initialized(self):
        params = self._block.collect_params()
        if all(p._ndarray is not None for p in params.values()):
            return
        # one throwaway eager forward over zeros finishes deferred init
        # (a stateful block's forward also takes its state tensors)
        zeros = [nd.zeros((1,) + s.row_shape, dtype=str(s.dtype))
                 for s in self._input_specs + self._state_specs]
        with autograd.pause(train_mode=False):
            self._block.forward(*zeros)

    def _graph_signature(self):
        """Process-stable model identity for the disk fingerprint: the
        nnvm JSON of the model's symbol graph (SymbolBlock carries it;
        other blocks are traced through the F=sym namespace, the
        ``export`` path). None when the block cannot symbol-trace —
        those sessions compile per process (memory-only executables)."""
        from .. import name as _name_mod
        from .. import symbol as sym
        from ..gluon.block import SymbolBlock

        try:
            if isinstance(self._block, SymbolBlock):
                return self._block._outputs.tojson()
            # a FRESH NameManager makes op-node names deterministic
            # (counter starts at zero per trace): the same model yields
            # the same JSON in every process — and on every re-trace —
            # so warm starts actually hit. Explicit names (param/input
            # variables) pass through untouched.
            with _name_mod.NameManager():
                out = self._block(*[sym.var(s.name)
                                    for s in self._input_specs
                                    + self._state_specs])
            if isinstance(out, (list, tuple)):
                out = sym.Group(list(out))
            return out.tojson()
        except Exception:
            return None

    # -- the pure function every bucket compiles ----------------------

    def _pure(self, param_vals, key, input_datas):
        """(param values, PRNG key, input arrays) -> tuple of output
        arrays; eval mode, no tape. The CachedOp._pure pattern without
        the mutation return path: serving forwards must be side-effect
        free, so trace-time parameter mutation is dropped (warned
        once)."""
        pnds = [p._ndarray for p in self._param_list]
        saved = [p._data for p in pnds]
        try:
            for p, v in zip(pnds, param_vals):
                p._data = v
            with autograd.pause(train_mode=False), \
                    mxrandom.key_provider(key):
                args = [NDArray(d) for d in input_datas]
                outs = self._block.forward(*args)
            if isinstance(outs, NDArray):
                flat = [outs]
            else:
                flat = [o for o in outs]
            # runs only while tracing, which _entry does under _lock
            self._num_outputs = len(flat)  # graft-lint: allow(L1102)
            if not self._mutation_warned and any(
                    p._data is not v
                    for p, v in zip(pnds, param_vals)):
                self._mutation_warned = True
                logging.warning(
                    "InferenceSession: forward mutated parameters "
                    "during the eval-mode trace; serving drops the "
                    "mutation (side-effect-free contract)")
            return tuple(o.data for o in flat)
        finally:
            for p, v in zip(pnds, saved):
                p._data = v

    def _pure_step(self, param_vals, key, input_datas, state_datas):
        """The stateful decode step :meth:`_pure` — ``(params, key,
        inputs, states) -> (*outputs, *new_states)`` flat. The state
        argument is DONATED by the compiled wrapper, so the block's
        new states reuse the old states' device buffers (state-in/
        state-out at zero copies); callers must hand in computation
        outputs, never device_put uploads (the fused_step.state_adopt
        laundering rule)."""
        pnds = [p._ndarray for p in self._param_list]
        saved = [p._data for p in pnds]
        try:
            for p, v in zip(pnds, param_vals):
                p._data = v
            with autograd.pause(train_mode=False), \
                    mxrandom.key_provider(key):
                args = [NDArray(d) for d in input_datas]
                sargs = [NDArray(d) for d in state_datas]
                outs = self._block.forward(*args, *sargs)
            flat = [outs] if isinstance(outs, NDArray) else list(outs)
            n_states = len(self._state_specs)
            if len(flat) <= n_states:
                raise MXNetError(
                    f"stateful forward returned {len(flat)} value(s); "
                    f"expected outputs followed by {n_states} new "
                    "state(s)")
            # runs only while tracing, under _step_entry's lock
            self._num_outputs = len(flat) - n_states  # graft-lint: allow(L1102)
            return tuple(o.data for o in flat)
        finally:
            for p, v in zip(pnds, saved):
                p._data = v

    # -- bucket resolution --------------------------------------------

    def _amp_version(self):
        from ..ndarray import registry as _op_registry

        return _op_registry.amp_version()

    def _jitted_for(self, amp_ver):
        """One jitted object PER AMP VERSION: ``jit(...).lower`` caches
        traces by aval, so re-lowering one shared jitted function after
        an ``amp.init()``/``disable()`` flip would replay the stale
        jaxpr — old casts baked in. A fresh function object per version
        gets a fresh trace cache (the CachedOp static-amp_ver pattern,
        without changing the executable's call signature)."""
        jf = self._jitted_by_ver.get(amp_ver)
        if jf is None:
            def pure(param_vals, key, input_datas):
                """Serving forward (AMP policy version %d)."""
                return self._pure(param_vals, key, input_datas)

            pure.__doc__ = pure.__doc__ % amp_ver
            jf = cc.counting_jit(pure, label="serving")
            self._jitted_by_ver[amp_ver] = jf
        return jf

    def _step_jitted_for(self, amp_ver):
        """The step-executable analog of :meth:`_jitted_for`, with the
        state argument donated: each decode step's new states reuse
        the previous states' buffers instead of growing the pool's
        working set per step."""
        jf = self._step_jitted_by_ver.get(amp_ver)
        if jf is None:
            def pure_step(param_vals, key, input_datas, state_datas):
                """Serving decode step (AMP policy version %d)."""
                return self._pure_step(param_vals, key, input_datas,
                                       state_datas)

            pure_step.__doc__ = pure_step.__doc__ % amp_ver
            jf = cc.counting_jit(pure_step, label="serving_step",
                                 donate_argnums=(3,))
            self._step_jitted_by_ver[amp_ver] = jf
        return jf

    def _graph_op_bodies(self):
        """The registered op functions the graph's nodes dispatch to —
        their bytecode digests salt the fingerprint (the round-9 rule:
        editing an op implementation must invalidate disk entries, not
        silently serve the old math)."""
        import json as _json

        from ..ndarray import _CAMEL_ALIASES
        from ..ndarray.registry import get_op

        bodies = []
        try:
            nodes = _json.loads(self._graph_sig)["nodes"]
        except Exception:
            return bodies
        for opname in sorted({n.get("op") or "null" for n in nodes}):
            if opname == "null":
                continue
            opdef = get_op(_CAMEL_ALIASES.get(opname, opname))
            if opdef is not None:
                bodies.append(opdef.fn)
        return bodies

    def _artifact(self, bucket, amp_ver):
        """The :class:`CompiledArtifact` for a bucket executable. Salt
        composition is declarative: graph-opt rewrites, a plan-sharded
        snapshot (GSPMD collectives baked in), and int8 lowering all
        change the lowered program without changing the source graph
        signature, so their providers fold into the fingerprint. A
        graph that cannot symbol-trace is memory-only (key None)."""
        if self._graph_sig is None:
            return CompiledArtifact("serving", None)
        from ..gluon.block import SymbolBlock

        key = ("serving", hashlib.sha256(
            self._graph_sig.encode()).hexdigest(),
            tuple(self._param_names),
            tuple((tuple(v.shape), str(v.dtype))
                  for v in self._param_vals),
            tuple((s.name, (bucket,) + s.row_shape, str(s.dtype))
                  for s in self._input_specs),
            amp_ver, bucket)
        code_of = [type(self)._pure, type(self._block).forward]
        code_of.extend(self._graph_op_bodies())
        return CompiledArtifact(
            "serving", key, code_of=tuple(code_of),
            salts=("graph_opt", "sharding", "quantize", "autotune"),
            salt_ctx={
                "optimizable": isinstance(self._block, SymbolBlock),
                "shard": self._shard,
                "graph_signature": self._graph_sig,
            })

    def _fingerprint(self, bucket, amp_ver):
        """Hex fingerprint of the bucket executable's artifact; None
        for a memory-only session (no graph signature)."""
        return self._artifact(bucket, amp_ver).fingerprint

    def _avals(self, bucket):
        import jax

        sds = jax.ShapeDtypeStruct
        # shape/dtype of a PRNG key WITHOUT drawing one: warmup must not
        # advance the ambient eager stream (PRNG neutrality, cf. the
        # round-9 Trainer.warmup contract)
        key = jax.random.PRNGKey(0)
        if self._shard is not None:
            rep = self._shard["rep"]
            param_avals = [sds(v.shape, v.dtype, sharding=sh)
                           for v, sh in zip(self._param_vals,
                                            self._shard["shardings"])]
            key_aval = sds(key.shape, key.dtype, sharding=rep)
            input_avals = [sds((bucket,) + s.row_shape, s.dtype,
                               sharding=rep)
                           for s in self._input_specs]
        else:
            param_avals = [sds(v.shape, v.dtype)
                           for v in self._param_vals]
            key_aval = sds(key.shape, key.dtype)
            input_avals = [sds((bucket,) + s.row_shape, s.dtype)
                           for s in self._input_specs]
        return param_avals, key_aval, input_avals

    def _entry(self, bucket):
        """The resolved executable for ``bucket`` under the CURRENT AMP
        policy (an ``amp.init()``/``disable()`` between calls re-resolves
        — AMP casts are baked into the trace, like CachedOp)."""
        amp_ver = self._amp_version()
        # double-checked: lock-free hit, miss re-checks under _lock
        ent = self._entries.get((bucket, amp_ver))  # graft-lint: allow(L1102)
        if ent is not None:
            return ent
        with self._lock:
            ent = self._entries.get((bucket, amp_ver))
            if ent is not None:
                return ent
            art = self._artifact(bucket, amp_ver)
            # meta is a callable: num_outputs is only known after the
            # trace runs (a warm process reads it from the envelope of
            # an executable it never traced)
            fn, meta, source = art.resolve(
                self._jitted_for(amp_ver), self._avals(bucket),
                # the meta lambda runs inside art.resolve, i.e.
                # under the _lock block that encloses this call
                meta=lambda: {"num_outputs":
                              self._num_outputs})  # graft-lint: allow(L1102)
            from_disk = source != "compile"
            if art.fingerprint is not None:
                self._artifact_fps.add(art.fingerprint)
            if from_disk:
                METRICS.bump("warm_disk_hits")
                if self._num_outputs is None:
                    self._num_outputs = meta.get("num_outputs")
            else:
                METRICS.bump("warm_compiles")
            ent = _BucketEntry(bucket, amp_ver, fn,
                               self._num_outputs, from_disk)
            self._entries[(bucket, amp_ver)] = ent
            return ent

    def _step_artifact(self, occupancy, amp_ver):
        """The :meth:`_artifact` analog for step executables, kind
        ``serving_step`` with a **state-shape salt**: the same graph
        served stateless and stateful lowers different programs (state
        threading + donation), so their disk artifacts must never
        collide. No sharding provider — the step path is single-device
        by construction (``shard_params`` rejects stateful sessions)."""
        if self._graph_sig is None:
            return CompiledArtifact("serving_step", None)
        from ..gluon.block import SymbolBlock

        key = ("serving_step", hashlib.sha256(
            self._graph_sig.encode()).hexdigest(),
            tuple(self._param_names),
            tuple((tuple(v.shape), str(v.dtype))
                  for v in self._param_vals),
            tuple((s.name, (occupancy,) + s.row_shape, str(s.dtype))
                  for s in self._input_specs),
            ("state",) + tuple(
                (s.name, (occupancy,) + s.row_shape, str(s.dtype))
                for s in self._state_specs),
            amp_ver, occupancy)
        code_of = [type(self)._pure_step, type(self._block).forward]
        code_of.extend(self._graph_op_bodies())
        store = self.state_store
        return CompiledArtifact(
            "serving_step", key, code_of=tuple(code_of),
            salts=("graph_opt", "quantize", "paged_state", "autotune"),
            salt_ctx={
                "optimizable": isinstance(self._block, SymbolBlock),
                "graph_signature": self._graph_sig,
                # paged-KV serving knobs re-key step artifacts; a
                # row-slot store contributes the empty salt, keeping
                # every pre-r21 fingerprint stable
                "paged": bool(store is not None and store.paged),
                "page_tokens": getattr(store, "page_tokens", 0),
                "kv_int8": bool(getattr(store, "kv_int8", False)),
            })

    def _step_avals(self, occupancy):
        import jax

        sds = jax.ShapeDtypeStruct
        key = jax.random.PRNGKey(0)
        param_avals = [sds(v.shape, v.dtype) for v in self._param_vals]
        input_avals = [sds((occupancy,) + s.row_shape, s.dtype)
                       for s in self._input_specs]
        state_avals = [sds((occupancy,) + s.row_shape, s.dtype)
                       for s in self._state_specs]
        return (param_avals, sds(key.shape, key.dtype), input_avals,
                state_avals)

    def _step_entry(self, occupancy):
        """The resolved step executable for an occupancy bucket under
        the current AMP policy (the :meth:`_entry` pattern). The step
        path is deliberately breaker-free: a systemic step failure
        fails the whole decode batch loudly in the batcher rather than
        demoting a bucket, and mixing step keys into ``_breakers``
        would poison ``degraded``'s sort."""
        amp_ver = self._amp_version()
        ent = self._step_entries.get((occupancy, amp_ver))
        if ent is not None:
            return ent
        with self._lock:
            ent = self._step_entries.get((occupancy, amp_ver))
            if ent is not None:
                return ent
            art = self._step_artifact(occupancy, amp_ver)
            fn, meta, source = art.resolve(
                self._step_jitted_for(amp_ver),
                self._step_avals(occupancy),
                # the meta lambda runs inside art.resolve, i.e.
                # under the _lock block that encloses this call
                meta=lambda: {"num_outputs":
                              self._num_outputs})  # graft-lint: allow(L1102)
            from_disk = source != "compile"
            if art.fingerprint is not None:
                self._artifact_fps.add(art.fingerprint)
            if from_disk:
                METRICS.bump("warm_disk_hits")
                if self._num_outputs is None:
                    self._num_outputs = meta.get("num_outputs")
            else:
                METRICS.bump("warm_compiles")
            ent = _BucketEntry(occupancy, amp_ver, fn,
                               self._num_outputs, from_disk)
            self._step_entries[(occupancy, amp_ver)] = ent
            return ent

    def warmup(self, buckets=None):
        """Resolve every bucket executable now (AOT compile, or disk
        deserialize on a warm start); stateful sessions resolve their
        occupancy-bucketed STEP executables instead. Returns
        ``{"disk_hits": n, "compiles": m}`` for this call."""
        hits = compiles = 0
        resolve = self._step_entry if self._state_specs else self._entry
        for b in (buckets or self.buckets):
            ent = resolve(int(b))
            if ent.from_disk:
                hits += 1
            else:
                compiles += 1
        return {"disk_hits": hits, "compiles": compiles}

    def artifact_fingerprints(self):
        """The fingerprints of every disk-cacheable executable this
        session resolved (buckets and step occupancies, across AMP
        versions) — the set a deployment bundle packs."""
        with self._lock:
            return sorted(self._artifact_fps)

    @property
    def warm(self):
        """True when every configured bucket is resolved under the
        current AMP policy (consistent read under the session lock —
        see :meth:`health_snapshot`)."""
        return self.health_snapshot()["warm"]

    # -- the request path ---------------------------------------------

    @property
    def input_specs(self):
        return list(self._input_specs)

    @property
    def num_outputs(self):
        # write-once value (set at first trace/envelope read); a racy
        # read sees None or the final count, never garbage
        return self._num_outputs  # graft-lint: allow(L1102)

    @property
    def max_batch(self):
        return self.buckets[-1]

    @property
    def stateful(self):
        """True when this session threads server-side state
        (constructed with ``state_shapes=``)."""
        return bool(self._state_specs)

    @property
    def state_specs(self):
        return list(self._state_specs)

    def refresh_params(self):
        """Re-snapshot parameter values from the block (after a live
        weight update). Executables are shape-keyed, so no recompile;
        a sharded session re-places the fresh snapshot at the plan's
        layouts (identity when the trainer already keeps them there)."""
        with self._lock:
            self._param_vals = [p._ndarray._data
                                for p in self._param_list]
            if self._shard is not None:
                self._param_vals = self._place_param_vals(
                    self._param_vals)

    # -- tensor-parallel serving --------------------------------------

    def _place_param_vals(self, vals):
        import jax

        return [v if getattr(v, "sharding", None) == sh
                else jax.device_put(v, sh)
                for v, sh in zip(vals, self._shard["shardings"])]

    def shard_params(self, plan=None, mesh=None):
        """Place the parameter snapshot per a :class:`ShardingPlan` and
        serve tensor-parallel: every bucket executable is (re)compiled
        with the plan's in-shardings, so a model bigger than one device
        serves from ONE sharded AOT program (GSPMD inserts the
        collectives). Defaults to the scoped ``sharding.plan_scope``
        pair. The AOT disk fingerprint is salted with the plan + mesh,
        so sharded and unsharded artifacts never collide; request
        inputs are replicated onto the mesh at upload, so callers keep
        passing plain host arrays. Returns ``self``."""
        from .. import sharding as _sharding

        if self._state_specs:
            raise MXNetError(
                "shard_params is not supported on stateful sessions "
                "(the state pool is single-device; shard the stateless "
                "prefill model instead)")
        if plan is None or mesh is None:
            ctx = _sharding.current_plan()
            if ctx is None:
                raise MXNetError(
                    "shard_params needs a plan: pass plan=/mesh= or "
                    "call inside sharding.plan_scope")
            plan = plan if plan is not None else ctx[0]
            mesh = mesh if mesh is not None else ctx[1]
        shardings = [
            _sharding.named_sharding(
                mesh, plan.spec_for(name, tuple(v.shape), mesh))
            for name, v in zip(self._param_names, self._param_vals)]
        with self._lock:
            self._shard = {
                "mesh": mesh,
                "shardings": shardings,
                "rep": _sharding.replicated(mesh),
                "plan": plan,  # the "sharding" salt provider reads it
            }
            self._param_vals = self._place_param_vals(self._param_vals)
            # compiled-at-old-layout executables (and their demotions)
            # are stale: drop them; the salted fingerprint resolves
            # fresh sharded ones on the next warmup()/request
            self._entries.clear()
            self._demoted.clear()
        _sharding._count("serving_sharded_sessions")
        return self

    @property
    def sharded(self):
        """True when the session serves from a plan-sharded snapshot."""
        return self._shard is not None

    def validate(self, *inputs):
        """Check request inputs against the session's input specs;
        returns (arrays, batch). NDArrays pass through untouched (the
        device-native path); everything else is coerced to a HOST numpy
        array of the spec dtype — deliberately not uploaded here, so
        batchers can coalesce and pad in pure numpy (no per-pattern XLA
        prim compiles) and pay exactly one device transfer per executed
        batch. Raises ``ValueError`` — the per-request failure a
        batcher reports on one future without poisoning its batch."""
        if len(inputs) != len(self._input_specs):
            raise ValueError(
                f"expected {len(self._input_specs)} input(s), got "
                f"{len(inputs)}")
        arrs, batch = [], None
        for x, spec in zip(inputs, self._input_specs):
            if isinstance(x, NDArray):
                # the bucket executables are traced at the spec dtype;
                # a mismatched device array would raise inside the AOT
                # Compiled and permanently degrade that bucket to the
                # jit path — reject it here, per-request
                if onp.dtype(x.dtype) != spec.dtype:
                    raise ValueError(
                        f"input {spec.name!r} dtype {x.dtype} != "
                        f"expected {spec.dtype}")
                arr = x
            else:
                try:
                    arr = onp.asarray(x, dtype=spec.dtype)
                except (TypeError, ValueError) as e:
                    raise ValueError(
                        f"input {spec.name!r} is not convertible to "
                        f"dtype {spec.dtype}: {e}") from None
            if tuple(arr.shape[1:]) != spec.row_shape:
                raise ValueError(
                    f"input {spec.name!r} row shape "
                    f"{tuple(arr.shape[1:])} != expected "
                    f"{spec.row_shape}")
            if batch is None:
                batch = arr.shape[0]
            elif arr.shape[0] != batch:
                raise ValueError("inputs disagree on batch size "
                                 f"({batch} vs {arr.shape[0]})")
            if batch == 0:
                raise ValueError("empty batch")
            arrs.append(arr)
        return arrs, batch

    def _bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _breaker(self, bucket, amp_ver):
        """The per-bucket circuit breaker (created on first use). One
        breaker per (bucket, AMP version) — an AMP flip re-resolves
        the executable, so its failure history starts clean too."""
        from ..resilience.breaker import CircuitBreaker

        # double-checked: lock-free hit, miss goes through the locked
        # setdefault below
        br = self._breakers.get((bucket, amp_ver))  # graft-lint: allow(L1102)
        if br is None:
            who = f"serving {self.label} " if self.label else "serving "
            with self._lock:
                br = self._breakers.setdefault(
                    (bucket, amp_ver),
                    CircuitBreaker(name=f"{who}bucket {bucket}"))
        return br

    def _record_bucket_failure(self, bucket, amp_ver, err):
        """Serving-side degradation policy: the FIRST failures demote
        the bucket from its AOT/deserialized executable back to the
        plain jit path (a corrupt or stale disk artifact must not
        poison the bucket forever — the jit path retraces fresh);
        failures past the breaker threshold open the circuit and the
        bucket fails fast (CircuitOpen -> HTTP 503) until the cooldown
        admits a probe. ``/healthz`` reflects both states."""
        from ..resilience import _count

        br = self._breaker(bucket, amp_ver)
        br.record_failure()
        key = (bucket, amp_ver)
        # double-checked: the demotion branch re-tests membership under
        # _lock before mutating
        if key not in self._demoted and br.failures >= 2:  # graft-lint: allow(L1102)
            with self._lock:
                ent = self._entries.get(key)
                if ent is not None and key not in self._demoted:
                    self._demoted.add(key)
                    ent.fn = self._jitted_for(amp_ver)
                    ent.from_disk = False
                    _count("breaker_demotions")
                    logging.warning(
                        "serving: bucket %d (amp v%d) failed "
                        "repeatedly (%s: %s); demoted its executable "
                        "to the jit path", bucket, amp_ver,
                        type(err).__name__, err)

    @property
    def degraded(self):
        """Buckets no longer running their AOT executable under the
        CURRENT AMP policy (demoted to the jit path), sorted.
        Snapshot under the lock: /healthz handler threads iterate
        while serving workers insert."""
        amp_ver = self._amp_version()
        with self._lock:
            demoted = set(self._demoted)
        return sorted(b for b, v in demoted if v == amp_ver)

    def breaker_states(self):
        """{bucket: breaker state} under the current AMP policy, for
        buckets that recorded at least one outcome. Snapshot under the
        lock (see ``degraded``)."""
        amp_ver = self._amp_version()
        with self._lock:
            breakers = dict(self._breakers)
        return {b: br.state for (b, v), br in breakers.items()
                if v == amp_ver}

    def health_snapshot(self):
        """One CONSISTENT health view for /healthz probes: warmth,
        demoted buckets, and breaker states read under a single
        acquisition of the session lock. The pre-round-23 surface
        stitched three independent reads (``warm`` / ``degraded`` /
        ``breaker_states``) together, so a probe racing a resolve or a
        demotion could report a bucket simultaneously warm and
        demoted; the L1102 guards audit flagged the lock-free reads as
        allow-pragma'd. Returns ``{"warm", "buckets",
        "degraded_buckets", "breaker_states", "open_buckets"}``."""
        amp_ver = self._amp_version()
        with self._lock:
            entries = self._step_entries if self._state_specs \
                else self._entries
            warm = all((b, amp_ver) in entries for b in self.buckets)
            demoted = set(self._demoted)
            breakers = dict(self._breakers)
        states = {b: br.state for (b, v), br in breakers.items()
                  if v == amp_ver}
        return {
            "warm": warm,
            "buckets": list(self.buckets),
            "degraded_buckets": sorted(
                b for b, v in demoted if v == amp_ver),
            "breaker_states": states,
            "open_buckets": sorted(
                b for b, s in states.items() if s != "closed"),
        }

    def _run_bucket(self, arrs, n):
        """Execute one <=max_batch slice through its bucket executable;
        returns the list of output jax arrays sliced back to ``n``
        rows. Host (numpy) inputs are padded in numpy and uploaded
        ONCE — no shape-dependent eager prims on the request path;
        device (NDArray) inputs pad on device. Failures feed the
        bucket's circuit breaker (see ``_record_bucket_failure``); an
        open breaker fails the request fast with CircuitOpen."""
        from ..resilience import faults as _faults

        bucket = self._bucket_for(n)
        amp_ver = self._amp_version()
        # lock-free fast read on the request path; a miss just means
        # the breaker isn't born yet (first failure creates it under
        # _lock in _breaker)
        br = self._breakers.get((bucket, amp_ver))  # graft-lint: allow(L1102)
        if br is not None:
            br.check()  # open circuit: fail fast (HTTP 503)
        # EVERY failure past the check must reach the breaker — entry
        # resolution, padding/upload, key draw and execution alike. A
        # half-open probe admitted by check() that died without a
        # recorded outcome would leak the probe slot and wedge the
        # bucket in fail-fast forever.
        try:
            from ..kernels import serving_fused as _sf

            ent = self._entry(bucket)
            fuse_pad = _sf.serving_fusion_enabled()
            datas = [None] * len(arrs)
            dev_idx, dev_arrs = [], []
            for i, a in enumerate(arrs):
                if isinstance(a, NDArray):
                    # device inputs: fused path pads ALL of them in
                    # one dispatch; legacy path pays one per input
                    dev_idx.append(i)
                    dev_arrs.append(a.data)
                else:
                    if a.shape[0] != bucket:
                        padded = onp.zeros((bucket,) + a.shape[1:],
                                           a.dtype)
                        padded[:a.shape[0]] = a
                        a = padded
                    datas[i] = nd.array(a).data
            if dev_arrs:
                if fuse_pad:
                    padded = _sf.pad_all(dev_arrs, bucket)
                else:
                    padded = [cc.pad_batch(d, bucket)
                              for d in dev_arrs]
                for i, p in zip(dev_idx, padded):
                    datas[i] = p
            key = mxrandom.next_key()
            if self._shard is not None:
                # inputs ride the mesh replicated (eager arrays commit
                # to one device; the sharded executable wants the full
                # device set) — params are already placed
                import jax

                rep = self._shard["rep"]
                datas = [jax.device_put(d, rep) for d in datas]
                key = jax.device_put(key, rep)
            # registered fault point: one bucket execution on the
            # serving request path
            _faults.maybe_fail("serving_execute")
            out = ent.fn(self._param_vals, key, datas)
        except Exception as e:
            self._record_bucket_failure(bucket, amp_ver, e)
            raise
        self._breaker(bucket, amp_ver).record_success()
        METRICS.bump("bucket_execs")
        METRICS.bump("padded_rows", bucket - n)
        METRICS.bump("true_rows", n)
        if bucket == n:
            return list(out)  # nothing padded: no slice op to pay
        if fuse_pad:
            return _sf.slice_all(list(out), bucket, n)
        return [cc.slice_batch(o, bucket, n) for o in out]

    # -- the stateful decode path -------------------------------------

    def _validate_states(self, states, batch):
        """Check explicit state arrays against the state specs (the
        :meth:`validate` contract applied to states: host arrays stay
        host-side, ``ValueError`` for per-request rejection)."""
        if len(states) != len(self._state_specs):
            raise ValueError(
                f"expected {len(self._state_specs)} state(s), got "
                f"{len(states)}")
        out = []
        for s, spec in zip(states, self._state_specs):
            if isinstance(s, NDArray):
                if onp.dtype(s.dtype) != spec.dtype:
                    raise ValueError(
                        f"state {spec.name!r} dtype {s.dtype} != "
                        f"expected {spec.dtype}")
                arr = s
            else:
                try:
                    arr = onp.asarray(s, dtype=spec.dtype)
                except (TypeError, ValueError) as e:
                    raise ValueError(
                        f"state {spec.name!r} is not convertible to "
                        f"dtype {spec.dtype}: {e}") from None
            if tuple(arr.shape[1:]) != spec.row_shape:
                raise ValueError(
                    f"state {spec.name!r} row shape "
                    f"{tuple(arr.shape[1:])} != expected "
                    f"{spec.row_shape}")
            if arr.shape[0] != batch:
                raise ValueError(
                    f"state {spec.name!r} batch {arr.shape[0]} != "
                    f"input batch {batch}")
            out.append(arr)
        return out

    def _run_step(self, arrs, states, n, adopted=False):
        """Execute one decode step at occupancy ``n`` through its
        occupancy-bucket step executable; returns ``(outputs,
        new_states)`` as jax arrays sliced back to ``n`` rows.

        The state argument is donated into the executable, and on
        jaxlib-0.4.37 CPU donating a ``device_put``-uploaded buffer
        corrupts unrelated live arrays (the fused_step ``state_adopt``
        hazard) — so host-origin states are laundered through
        ``jnp.array(..., copy=True)`` after upload, making every
        donated buffer an XLA computation output. ``adopted=True`` is
        the batcher's fast path: the states are ``SessionStateStore.
        gather`` outputs (already computation outputs), donated
        as-is."""
        import jax.numpy as jnp

        from ..resilience import faults as _faults

        bucket = self._bucket_for(n)
        ent = self._step_entry(bucket)
        datas = []
        for a in arrs:
            if isinstance(a, NDArray):
                datas.append(cc.pad_batch(a.data, bucket))
            else:
                if a.shape[0] != bucket:
                    padded = onp.zeros((bucket,) + a.shape[1:], a.dtype)
                    padded[:a.shape[0]] = a
                    a = padded
                datas.append(nd.array(a).data)
        sdatas = []
        for s, spec in zip(states, self._state_specs):
            if adopted:
                # gather/pad outputs are computation outputs:
                # donation-safe without laundering
                sdatas.append(s if s.shape[0] == bucket
                              else cc.pad_batch(s, bucket))
                continue
            if isinstance(s, NDArray):
                d = cc.pad_batch(s.data, bucket)
            else:
                if s.shape[0] != bucket:
                    padded = onp.zeros((bucket,) + s.shape[1:], s.dtype)
                    padded[:s.shape[0]] = s
                    s = padded
                d = nd.array(s).data
            sdatas.append(jnp.array(d, copy=True))
        key = mxrandom.next_key()
        # same registered fault point as the stateless request path:
        # one executable invocation on the serving hot path
        _faults.maybe_fail("serving_execute")
        out = ent.fn(self._param_vals, key, datas, sdatas)
        METRICS.bump("bucket_execs")
        METRICS.bump("padded_rows", bucket - n)
        METRICS.bump("true_rows", n)
        outs = list(out[:ent.num_outputs])
        news = list(out[ent.num_outputs:])
        if bucket != n:
            outs = [cc.slice_batch(o, bucket, n) for o in outs]
            news = [cc.slice_batch(s, bucket, n) for s in news]
        return outs, news

    def step(self, *inputs, states):
        """One incremental decode step with EXPLICIT states: ``(one
        row-batch of inputs, current states) -> (outputs, new
        states)``. This is the single-process stateful API (offline
        decode loops, tests, benchmarks); served traffic goes through
        a stateful ``DynamicBatcher``, which keeps states server-side
        in the session's :class:`~.state.SessionStateStore` and only
        ever passes slot gathers. Occupancy above ``max_batch`` is
        rejected (a decode step is never chunked — states would
        cross-talk)."""
        if not self._state_specs:
            raise MXNetError("step() requires a stateful session "
                             "(construct with state_shapes=)")
        arrs, batch = self.validate(*inputs)
        if batch > self.max_batch:
            raise ValueError(
                f"step occupancy {batch} exceeds max_batch "
                f"{self.max_batch}")
        svals = self._validate_states(states, batch)
        t0 = time.perf_counter()
        outs, news = self._run_step(arrs, svals, batch)
        import jax

        jax.block_until_ready(outs + news)
        METRICS.bump("decode_steps")
        METRICS.observe_batch(batch, time.perf_counter() - t0)
        result = tuple(NDArray(o) for o in outs)
        return (result[0] if len(result) == 1 else result,
                [NDArray(s) for s in news])

    def close(self):
        """Release resources a stateful session owns (its state
        store's metrics probe). Stateless sessions: no-op."""
        if self._owns_store and self.state_store is not None:
            self.state_store.close()

    def predict(self, *inputs):
        """Run eval-mode inference. Inputs may be NDArrays or anything
        ``numpy.asarray`` accepts (batch axis first). Batches larger
        than ``max_batch`` are chunked. Returns an NDArray (single
        output) or tuple of NDArrays."""
        if self._state_specs:
            raise MXNetError(
                "predict() is stateless; this session threads state — "
                "use step() or a stateful DynamicBatcher")
        arrs, batch = self.validate(*inputs)
        t0 = time.perf_counter()
        chunks = []
        start = 0
        while start < batch:
            n = min(self.max_batch, batch - start)
            if start == 0 and n == batch:
                chunk = arrs  # whole request fits one bucket: no slice
            else:
                chunk = [NDArray(a.data[start:start + n])
                         if isinstance(a, NDArray) else
                         a[start:start + n] for a in arrs]
            chunks.append(self._run_bucket(chunk, n))
            start += n
        if len(chunks) == 1:
            outs = chunks[0]
        else:
            import jax.numpy as jnp

            outs = [jnp.concatenate([c[i] for c in chunks], axis=0)
                    for i in range(len(chunks[0]))]
        # sync before stamping: jax dispatch is asynchronous, and an
        # unsynced stamp would report enqueue time as exec latency
        import jax

        jax.block_until_ready(outs)
        METRICS.observe_batch(batch, time.perf_counter() - t0)
        result = tuple(NDArray(o) for o in outs)
        return result[0] if len(result) == 1 else result

    def __call__(self, *inputs):
        return self.predict(*inputs)

    def __repr__(self):
        return (f"InferenceSession({type(self._block).__name__}, "
                f"inputs={self._input_specs}, buckets={self.buckets}, "
                f"warm={self.warm})")
