"""Stdlib HTTP front end: JSON / npy inference over ThreadingHTTPServer.

The network face of the serving subsystem (reference analog: the MXNet
model-server REST surface). Deliberately stdlib-only — no framework
dependency beyond numpy, which the package already requires — so a
serving container needs nothing the training image doesn't have.

Endpoints:

- ``POST /predict`` — ``application/json`` body ``{"data": <nested
  list>}`` (or ``{"inputs": [<list>, ...]}`` for multi-input models)
  returns ``{"outputs": [...], "shapes": [...]}``; raw
  ``application/x-npy`` body returns the first output as npy bytes.
  ``POST /models/<name>/predict`` targets one model of a
  :class:`~mxnet_tpu.serving.repository.ModelRepository`. Requests
  carry their SLO class and deadline via the ``X-SLO-Class`` /
  ``X-Timeout-Ms`` headers or the JSON fields ``slo_class`` /
  ``timeout_ms`` (body wins). Stateful (continuous-batching) models
  additionally take a session affinity key via ``X-Session-Id`` or the
  JSON field ``session_id`` (body wins) — every decode step of one
  stream must carry the same id.
- ``GET /healthz`` — liveness + warm state (``200`` once every bucket
  executable is resolved; load balancers gate on this so a cold
  replica never takes traffic) plus the degradation ladder: per-class
  queue depths, the live SLO-headroom block, per-bucket circuit
  state, and — in repository mode — per-model canary status.
- ``GET /models`` — repository mode: the model/version/canary listing.
- ``GET /metrics`` — Prometheus text exposition of the process-wide
  serving registry.

Error mapping: validation ``ValueError`` -> 400, queue backpressure
(:class:`~mxnet_tpu.serving.batcher.ServerBusy`) -> 503, a mid-stream
state eviction (:class:`~mxnet_tpu.serving.state.SessionEvicted`) ->
503 with ``Retry-After`` (the client restarts its stream), admission
shed (:class:`~mxnet_tpu.serving.admission.ShedLoad`) -> fast 503
with a ``Retry-After`` header, deadline
(:class:`~mxnet_tpu.serving.batcher.RequestTimeout` or a result-wait
timeout) -> 504, anything else -> 500. ``stop()`` is graceful: the
listener closes first, then the batcher drains (engine.close() order —
no accepted request is dropped).
"""
from __future__ import annotations

import io
import json
import logging
import pickle
import threading
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as onp

from ..resilience.breaker import CircuitOpen
from ..telemetry import metrics as _tmetrics
from ..telemetry import tracer as _telem
from .admission import ShedLoad, normalize_class
from .batcher import DynamicBatcher, RequestTimeout, ServerBusy
from .metrics import METRICS
from .state import SessionEvicted

__all__ = ["ModelServer"]

_MAX_BODY = 64 * 1024 * 1024  # 64 MiB request-body bound


class ModelServer:
    """HTTP serving endpoint over an InferenceSession / DynamicBatcher
    / ModelRepository.

    ``ModelServer(session)`` owns a batcher built from the
    ``MXNET_SERVING_*`` knobs; pass ``batcher=`` to share an existing
    one (it will NOT be closed on ``stop()``); pass ``repository=`` to
    front a multi-model :class:`ModelRepository` (closed on ``stop()``
    — the server is its lifecycle owner, engine.close() order).
    ``port=0`` binds an ephemeral port (tests); read it back via
    ``server.port`` after ``start()``."""

    def __init__(self, session=None, batcher=None, repository=None,
                 host=None, port=None):
        from .. import env as _env

        if sum(x is not None for x in (session, batcher,
                                       repository)) != 1:
            raise ValueError("exactly one of session= / batcher= / "
                             "repository= is required")
        self.repository = repository
        self._own_batcher = batcher is None and repository is None
        if repository is not None:
            self.batcher = None
            self.session = None
        else:
            self.batcher = batcher or DynamicBatcher(session)
            self.session = session or self.batcher.session
        self._host = host if host is not None else _env.get_str(
            "MXNET_SERVING_HOST", "127.0.0.1")
        self._port = int(port if port is not None else _env.get_int(
            "MXNET_SERVING_PORT", 8080))
        self._httpd = None
        self._thread = None

    # -- lifecycle -----------------------------------------------------

    def start(self):
        """Bind and serve in a daemon thread; returns self."""
        if self._httpd is not None:
            return self
        server = self

        class _Handler(_ServingHandler):
            model_server = server

        self._httpd = ThreadingHTTPServer((self._host, self._port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="mxnet-serving-http", daemon=True)
        self._thread.start()
        return self

    @property
    def port(self):
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._port

    @property
    def address(self):
        return f"http://{self._host}:{self.port}"

    def stop(self):
        """Graceful shutdown: close the listener (stop accepting),
        then drain the batcher (owned batchers only). Idempotent."""
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._own_batcher:
            self.batcher.close()
        if self.repository is not None:
            self.repository.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class _ServingHandler(BaseHTTPRequestHandler):
    model_server = None  # bound per-server by ModelServer.start
    protocol_version = "HTTP/1.1"
    _request_id = None  # set per-request at the top of do_POST
    _status = None      # last reply's status code (span attr)

    # -- plumbing ------------------------------------------------------

    def log_message(self, fmt, *args):  # default: stderr spam
        logging.debug("serving http: " + fmt, *args)

    def _reply(self, code, body, content_type="application/json",
               headers=None):
        if isinstance(body, (dict, list)):
            body = json.dumps(body).encode()
        self._status = code
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if self._request_id is not None:
            # the request's trace id, echoed on EVERY response —
            # success or error — so a client log line joins the
            # server-side trace without guessing
            self.send_header("X-Request-Id", self._request_id)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code, message, headers=None, retry_after_s=None):
        """One error envelope for every failure class: ``error`` +
        ``request_id`` (when the request reached routing) +
        ``retry_after_s`` (the backoff hint, null when retrying can't
        help — 400s, timeouts). A non-null hint also rides the
        standard ``Retry-After`` header for clients that only read
        headers."""
        doc = {"error": message,
               "request_id": self._request_id,
               "retry_after_s": retry_after_s}
        if retry_after_s is not None:
            headers = dict(headers or {})
            headers.setdefault("Retry-After",
                               f"{max(retry_after_s, 0.0):.3f}")
        self._reply(code, doc, headers=headers)

    # -- GET -----------------------------------------------------------

    def do_GET(self):
        srv = self.model_server
        if self.path == "/healthz":
            if srv.repository is not None:
                doc = srv.repository.healthz()
                self._reply(200 if doc["warm"] else 503, doc)
                return
            session = srv.session
            # resilience state rides along: buckets demoted to the jit
            # path and open circuit breakers (serving/session.py). A
            # degraded-but-warm replica still answers 200 — it serves,
            # just slower — so the LB keeps it while operators see the
            # "degraded" status and act on it. ONE consistent snapshot
            # under the session lock (round 23) — the old per-field
            # reads could stitch a bucket both warm and demoted
            if hasattr(session, "health_snapshot"):
                snap = session.health_snapshot()
            else:
                snap = {"warm": True, "buckets": [],
                        "degraded_buckets": [], "open_buckets": []}
            warm = bool(snap["warm"])
            status = "ok" if warm else "warming"
            if warm and (snap["degraded_buckets"]
                         or snap["open_buckets"]):
                status = "degraded"
            adm = getattr(srv.batcher, "admission", None)
            store = getattr(session, "state_store", None)
            # 503 until warm so a status-code health check (the
            # standard LB kind) keeps traffic off a cold replica
            self._reply(200 if warm else 503, {
                "status": status,
                "warm": warm,
                "buckets": list(snap["buckets"]),
                "degraded_buckets": snap["degraded_buckets"],
                "open_buckets": snap["open_buckets"],
                "queue_depth": srv.batcher.qsize(),
                # round 23: capacity rides along so a fleet router can
                # aggregate gossiped depth/capacity into its own
                # admission ladder without a second endpoint
                "queue_capacity": srv.batcher.queue_capacity(),
                # the ROADMAP "budget signal": how much SLO headroom is
                # left (1.0 idle .. 0.0 blown) and who is shedding
                "queue_depths": srv.batcher.qsize_by_class(),
                "slo": adm.snapshot() if adm is not None else None,
                # stateful serving: live session-state pool occupancy
                "state": store.stats() if store is not None else None})
        elif self.path == "/admin/export_state":
            # fleet drain (round 23): hand this replica's live decode
            # state to the router, which repartitions it onto peers.
            # Dense-row export (round 16/21) crosses paging geometries,
            # so the receiving replica may run different PAGE_TOKENS /
            # KV quantization. Internal surface — pickle, like bundles.
            store = getattr(srv.session, "state_store", None) \
                if srv.session is not None else None
            if store is None:
                self._error(409, "no session state store behind this "
                                 "server (stateless or repository "
                                 "mode)")
                return
            payload = pickle.dumps(store.export_state(),
                                   protocol=pickle.HIGHEST_PROTOCOL)
            self._reply(200, payload,
                        content_type="application/octet-stream")
        elif self.path == "/models":
            if srv.repository is None:
                self._error(404, "no model repository behind this "
                                 "server")
                return
            self._reply(200, {
                "default": srv.repository.default_model,
                "models": srv.repository.model_states()})
        elif self.path == "/metrics":
            # round 18: the UNIFIED exposition — the serving
            # histogram/label block exactly as before, plus every
            # training-side counter family (fused_step, pipeline,
            # compile_cache, ...), scrapeable from one endpoint
            self._reply(200, _tmetrics.prometheus_text().encode(),
                        content_type="text/plain; version=0.0.4")
        else:
            self._error(404, f"no route {self.path!r}")

    # -- POST ----------------------------------------------------------

    def _route_model(self):
        """Resolve the POST path to a model name (repository mode) or
        None (single-session mode). Raises LookupError for unroutable
        paths."""
        srv = self.model_server
        if self.path in ("/predict", "/invocations"):
            if srv.repository is not None:
                name = srv.repository.default_model
                if name is None:
                    raise LookupError("repository has no models")
                return name
            return None
        parts = self.path.strip("/").split("/")
        if (len(parts) == 3 and parts[0] == "models" and
                parts[2] in ("predict", "invocations") and
                srv.repository is not None):
            if parts[1] not in srv.repository.models():
                raise LookupError(f"unknown model {parts[1]!r}")
            return parts[1]
        raise LookupError(f"no route {self.path!r}")

    def do_POST(self):
        # request-scoped trace propagation: adopt the client's
        # ``X-Request-Id`` (minting one when absent), scope every span
        # of this request to it — on this handler thread via
        # trace_context, across the queue via ``_Request.trace_id`` —
        # and echo it on the response, errors included.
        self._request_id = (self.headers.get("X-Request-Id") or
                            _telem.new_trace_id())
        with _telem.trace_context(self._request_id):
            with _telem.span("serving.request", cat="serving",
                             path=self.path) as sp:
                self._do_post()
                sp.set(status=self._status)

    def _restore_state(self):
        """POST /admin/restore_state — fleet drain receive side: a
        pickled ``export_state`` payload (possibly a repartitioned
        subset) lands in this replica's state pool. Replies with the
        number of sessions restored."""
        srv = self.model_server
        store = getattr(srv.session, "state_store", None) \
            if srv.session is not None else None
        if store is None:
            self._error(409, "no session state store behind this "
                             "server (stateless or repository mode)")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            self._error(400, "bad Content-Length")
            return
        if length <= 0 or length > _MAX_BODY:
            self._error(400, f"body length {length} out of bounds "
                             f"(max {_MAX_BODY})")
            return
        try:
            payload = pickle.loads(self.rfile.read(length))
            restored = store.restore_state(payload)
        except Exception as e:  # noqa: BLE001 — HTTP boundary
            self._error(400, f"unrestorable state payload: "
                             f"{type(e).__name__}: {e}")
            return
        self._reply(200, {"restored": int(restored)})

    def _do_post(self):
        if self.path == "/admin/restore_state":
            self._restore_state()
            return
        try:
            model = self._route_model()
        except LookupError as e:
            self._error(404, str(e))
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            self._error(400, "bad Content-Length")
            return
        if length <= 0 or length > _MAX_BODY:
            self._error(400, f"body length {length} out of bounds "
                             f"(max {_MAX_BODY})")
            return
        body = self.rfile.read(length)
        ctype = (self.headers.get("Content-Type") or
                 "application/json").split(";")[0].strip().lower()
        # SLO class + deadline ride headers for every content type;
        # JSON bodies may override (body wins — it travels with the
        # payload through proxies that strip custom headers)
        slo_class = self.headers.get("X-SLO-Class")
        timeout_ms = self.headers.get("X-Timeout-Ms")
        session_id = self.headers.get("X-Session-Id")
        try:
            if ctype == "application/x-npy":
                inputs = [onp.load(io.BytesIO(body), allow_pickle=False)]
                as_npy = True
            else:
                doc = json.loads(body)
                if isinstance(doc, dict):
                    slo_class = doc.get("slo_class", slo_class)
                    timeout_ms = doc.get("timeout_ms", timeout_ms)
                    session_id = doc.get("session_id", session_id)
                if isinstance(doc, dict) and "inputs" in doc:
                    inputs = [onp.asarray(x) for x in doc["inputs"]]
                elif isinstance(doc, dict) and "data" in doc:
                    inputs = [onp.asarray(doc["data"])]
                else:
                    raise ValueError(
                        'JSON body must carry "data" or "inputs"')
                as_npy = False
            slo_class = normalize_class(slo_class)
            timeout_ms = float(timeout_ms) if timeout_ms is not None \
                else None
        except ValueError as e:
            self._error(400, f"unparseable request body: {e}")
            return
        srv = self.model_server
        kw = {} if session_id is None else {"session_id": session_id}
        try:
            if model is not None:
                outs = srv.repository.predict(
                    model, *inputs, timeout_ms=timeout_ms,
                    slo_class=slo_class, **kw)
            else:
                outs = srv.batcher.predict(
                    *inputs, timeout_ms=timeout_ms, slo_class=slo_class,
                    **kw)
        except ValueError as e:
            self._error(400, str(e))
            return
        except ShedLoad as e:
            # admission control said no BEFORE queueing: fast 503 with
            # the backoff hint — a well-behaved client honors it
            METRICS.bump("rejected")
            self._error(503, str(e),
                        retry_after_s=max(e.retry_after_s, 0.0))
            return
        except SessionEvicted as e:
            # the stream's state slot is gone (TTL/LRU/injected): a
            # clean retryable 503 — the client re-opens its stream and
            # replays; ordered before the plain ServerBusy mapping
            # (SessionEvicted subclasses it)
            self._error(503, str(e), retry_after_s=0.0)
            return
        except (ServerBusy, CircuitOpen) as e:
            # both are "back off and retry later": queue backpressure,
            # or this bucket's circuit is open during its cooldown
            self._error(503, str(e), retry_after_s=0.05)
            return
        except (RequestTimeout, _FutureTimeout) as e:
            self._error(504, str(e) or "request timed out")
            return
        except Exception as e:  # noqa: BLE001 — HTTP boundary
            logging.exception("serving: predict failed")
            self._error(500, f"{type(e).__name__}: {e}")
            return
        outs = outs if isinstance(outs, tuple) else (outs,)
        outs = [onp.asarray(o) for o in outs]  # batcher yields host arrays
        if as_npy:
            buf = io.BytesIO()
            onp.save(buf, outs[0])
            self._reply(200, buf.getvalue(),
                        content_type="application/x-npy")
        else:
            self._reply(200, {
                "outputs": [o.tolist() for o in outs],
                "shapes": [list(o.shape) for o in outs]})
