"""Server-side session state: the memory of incremental decode.

Stateless serving re-executes a sequence's whole prefix on every token
— O(prefix) work per step. This module keeps each client's recurrent /
KV state ON THE SERVER, in a preallocated device-resident pool, so a
decode step costs exactly one cell forward regardless of position
(the continuous-batching literature's KV-cache discipline applied to
the round-10 serving stack).

:class:`SessionStateStore` holds one **slot** per live session: for
every state tensor the model threads, the store owns a device array of
shape ``(num_slots,) + row_shape`` allocated once at construction.
Sessions are *slot-indexed*, not shape-indexed — a decode batch gathers
whichever slots are live into a dense ``(occupancy, ...)`` block, runs
ONE compiled step executable, and scatters the new state back — so a
single AOT program serves any batch membership, exactly the bucketing
discipline the rest of the stack lives by.

Policies:

- **Affinity** — a session's steps never interleave: the store marks a
  slot ``in_flight`` while a step batch holds it, the continuous
  batcher admits at most one queued step per session into a batch, and
  eviction never touches an in-flight slot.
- **TTL + LRU under a byte budget** — the pool is sized by
  ``MXNET_SERVING_STATE_SLOTS`` capped by
  ``MXNET_SERVING_STATE_BUDGET_MB``; opening a session when every slot
  is taken first reclaims idle-expired sessions
  (``MXNET_SERVING_STATE_TTL_S``), then the least-recently-stepped one.
  An evicted session's next step raises :class:`SessionEvicted` — a
  clean, retryable 503 telling exactly that one client to re-open.
- **Checkpointable** — :meth:`export_state` / :meth:`restore_state`
  round-trip every live session as host arrays; the round-12
  ``CheckpointManager(session_state=store)`` rides them in its
  manifest-hashed payload, and a round-13 canary promote migrates live
  sessions into the new version's store instead of dropping them
  (``resumed_sessions`` counts both paths).

The ``session_state_evict`` fault seam fires in :meth:`acquire` —
chaos drills can evict any session mid-stream and assert the blast
radius is one client.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, deque

import numpy as onp

from ..base import MXNetError
from .batcher import ServerBusy
from .metrics import METRICS

__all__ = ["SessionStateStore", "SessionEvicted"]

#: evicted-session tombstones kept for clean error reporting; past the
#: bound the oldest fold into the generic "unknown session" error
_TOMBSTONES = 4096


class SessionEvicted(ServerBusy):
    """This session's server-side state slot was reclaimed (idle TTL,
    LRU pressure under the byte budget, or an injected fault) — the
    stream cannot continue from server state. Retryable: re-open the
    session (optionally from a checkpoint) and resume. Maps to HTTP
    503 with a Retry-After hint, and is delivered to exactly the one
    client whose slot went away."""


class _Slot:
    """One live session's bookkeeping (state lives in the pool)."""

    __slots__ = ("sid", "slot", "created", "last_used", "steps",
                 "in_flight")

    def __init__(self, sid, slot, now):
        self.sid = sid
        self.slot = slot
        self.created = now
        self.last_used = now
        self.steps = 0
        self.in_flight = False


class SessionStateStore:
    """Slot-indexed, device-resident per-session state pool.

    Parameters
    ----------
    state_shapes : sequence of shape tuples
        Per-state ROW shapes (no batch axis), e.g. ``[(256,), (256,)]``
        for an LSTM — ``RecurrentCell.state_row_shapes()`` emits them.
    state_dtypes : sequence of dtypes, optional (default float32)
    max_sessions : int, optional — slot count before the byte budget
        (default ``MXNET_SERVING_STATE_SLOTS``)
    byte_budget : int, optional — pool byte cap; shrinks the slot
        count to fit (default ``MXNET_SERVING_STATE_BUDGET_MB`` MiB)
    ttl_s : float, optional — idle expiry (default
        ``MXNET_SERVING_STATE_TTL_S``); <= 0 disables
    label : str, optional — logging/debug tag
    """

    def __init__(self, state_shapes, state_dtypes=None, max_sessions=None,
                 byte_budget=None, ttl_s=None, label=None):
        import jax.numpy as jnp

        from .. import env as _env

        self.label = label
        self.state_shapes = tuple(tuple(int(d) for d in s)
                                  for s in state_shapes)
        if not self.state_shapes:
            raise MXNetError("state_shapes must name at least one "
                             "state tensor")
        dts = state_dtypes or ["float32"] * len(self.state_shapes)
        if len(dts) != len(self.state_shapes):
            raise MXNetError("state_dtypes length must match "
                             "state_shapes")
        self.state_dtypes = tuple(onp.dtype(d) for d in dts)
        self.bytes_per_session = int(sum(
            int(onp.prod(s or (1,))) * dt.itemsize
            for s, dt in zip(self.state_shapes, self.state_dtypes)))
        slots = int(max_sessions if max_sessions is not None else
                    _env.get_int("MXNET_SERVING_STATE_SLOTS", 64))
        budget = int(byte_budget if byte_budget is not None else
                     _env.get_int("MXNET_SERVING_STATE_BUDGET_MB", 64)
                     * 1024 * 1024)
        if budget > 0:
            slots = min(slots, max(budget // self.bytes_per_session, 1))
        self.num_slots = max(slots, 1)
        self.ttl_s = float(ttl_s if ttl_s is not None else
                           _env.get_float("MXNET_SERVING_STATE_TTL_S",
                                          600.0))
        # the pool: ONE preallocated device array per state tensor —
        # gather/scatter are XLA ops over it, never per-session uploads
        self._pools = [jnp.zeros((self.num_slots,) + s, dtype=str(dt))
                       for s, dt in zip(self.state_shapes,
                                        self.state_dtypes)]
        self._lock = threading.RLock()
        self._slots = OrderedDict()  # sid -> _Slot, LRU order
        self._free = list(range(self.num_slots - 1, -1, -1))
        self._evicted = OrderedDict()  # sid -> reason (tombstones)
        self.steps_total = 0
        self._occupancy_token = METRICS.register_occupancy_probe(
            lambda: len(self._slots))

    # -- introspection -------------------------------------------------

    @property
    def occupancy(self):
        with self._lock:
            return len(self._slots)

    def has(self, sid):
        with self._lock:
            return sid in self._slots

    def live_sessions(self):
        with self._lock:
            return list(self._slots)

    def stats(self):
        """Flat description for /healthz and admission probes."""
        with self._lock:
            return {"sessions": len(self._slots),
                    "slots": self.num_slots,
                    "bytes_per_session": self.bytes_per_session,
                    "ttl_s": self.ttl_s,
                    "steps_total": self.steps_total}

    # -- lifecycle -----------------------------------------------------

    def open(self, sid, init_states=None, _resumed=False):
        """Allocate (or return) the state slot for ``sid``. A fresh
        slot starts at zeros unless ``init_states`` (per-state ROW
        arrays) seeds it. Reclaims TTL-expired then LRU slots when
        full; raises :class:`ServerBusy` only when every slot is
        pinned by an in-flight step batch. Idempotent for an already
        open session (``init_states`` then rewrites its state)."""
        import jax.numpy as jnp

        sid = str(sid)
        with self._lock:
            rec = self._slots.get(sid)
            if rec is None:
                if not self._free:
                    self._reclaim_locked()
                if not self._free:
                    raise ServerBusy(
                        f"no free session-state slot ({self.num_slots} "
                        "slots, all in flight); retry later")
                rec = _Slot(sid, self._free.pop(), time.monotonic())
                self._slots[sid] = rec
                self._evicted.pop(sid, None)
                # a reused slot still holds the previous tenant's
                # state: reset it (zeros) or seed it before anyone
                # gathers
                if init_states is None:
                    for i, pool in enumerate(self._pools):
                        self._pools[i] = pool.at[rec.slot].set(0)
            if init_states is not None:
                if len(init_states) != len(self._pools):
                    raise MXNetError(
                        f"expected {len(self._pools)} state tensor(s), "
                        f"got {len(init_states)}")
                for i, (pool, s) in enumerate(zip(self._pools,
                                                  init_states)):
                    row = jnp.asarray(onp.asarray(
                        s, dtype=self.state_dtypes[i]))
                    if tuple(row.shape) != self.state_shapes[i]:
                        raise MXNetError(
                            f"state {i} row shape {tuple(row.shape)} "
                            f"!= expected {self.state_shapes[i]}")
                    self._pools[i] = pool.at[rec.slot].set(row)
            if _resumed:
                METRICS.bump("resumed_sessions")
            return rec.slot

    def open_for_step(self, sid):
        """The batcher's IMPLICIT open — a stream's first step
        allocates its slot on arrival. Unlike :meth:`open` (the
        explicit client re-open, which clears any tombstone), this
        refuses evicted sessions: a pipelined stream whose slot went
        away must see :class:`SessionEvicted` on every remaining step,
        never a silent restart from zero state."""
        with self._lock:
            if sid not in self._slots:
                reason = self._evicted.get(sid)
                if reason is not None:
                    raise SessionEvicted(
                        f"session {sid!r} state was evicted ({reason}); "
                        "re-open the session and retry")
            return self.open(sid)

    def _reclaim_locked(self):
        """Refill ``_free`` by one slot: TTL-expired sessions first
        (all of them — they are dead weight), then the LRU session.
        In-flight slots are never reclaimed (affinity)."""
        now = time.monotonic()
        if self.ttl_s > 0:
            for sid in [s for s, r in self._slots.items()
                        if not r.in_flight and
                        now - r.last_used > self.ttl_s]:
                self._evict_locked(sid, "idle TTL expired")
        if self._free:
            return
        for sid, rec in self._slots.items():  # OrderedDict = LRU order
            if not rec.in_flight:
                self._evict_locked(sid, "LRU pressure (pool full)")
                return

    def _evict_locked(self, sid, reason):
        rec = self._slots.pop(sid)
        self._free.append(rec.slot)
        self._evicted[sid] = reason
        while len(self._evicted) > _TOMBSTONES:
            self._evicted.popitem(last=False)
        METRICS.bump("evictions")
        logging.info("serving%s: session %s evicted after %d step(s): "
                     "%s", f" {self.label}" if self.label else "", sid,
                     rec.steps, reason)

    def evict(self, sid, reason="operator request"):
        """Explicitly drop one session's state (no-op if unknown)."""
        with self._lock:
            if sid in self._slots:
                self._evict_locked(sid, reason)

    def acquire(self, sid):
        """Pin ``sid``'s slot for one decode step; returns the slot
        record. The ``session_state_evict`` fault seam fires here —
        an injected fire evicts THIS session and raises
        :class:`SessionEvicted`, so chaos drills hit exactly one
        client. TTL expiry is also enforced here (the lazy half of
        reclamation). Pair with :meth:`release`."""
        from ..resilience import faults as _faults
        from ..resilience.faults import InjectedFault

        with self._lock:
            rec = self._slots.get(sid)
            if rec is None:
                reason = self._evicted.get(sid)
                if reason is not None:
                    raise SessionEvicted(
                        f"session {sid!r} state was evicted ({reason}); "
                        "re-open the session and retry")
                raise MXNetError(
                    f"unknown session {sid!r} (never opened on this "
                    "server)")
            if rec.in_flight:
                raise MXNetError(
                    f"session {sid!r} already has a step in flight "
                    "(affinity violation — one step at a time)")
            try:
                _faults.maybe_fail("session_state_evict")
            except InjectedFault as e:
                self._evict_locked(sid, f"injected fault ({e})")
                raise SessionEvicted(
                    f"session {sid!r} state was evicted (injected "
                    "fault); re-open the session and retry") from e
            now = time.monotonic()
            if self.ttl_s > 0 and now - rec.last_used > self.ttl_s:
                self._evict_locked(sid, "idle TTL expired")
                raise SessionEvicted(
                    f"session {sid!r} state expired after "
                    f"{self.ttl_s:g}s idle; re-open the session and "
                    "retry")
            rec.in_flight = True
            rec.last_used = now
            self._slots.move_to_end(sid)
            return rec

    def release(self, rec, stepped=True):
        """Unpin a slot after its step batch resolves."""
        with self._lock:
            rec.in_flight = False
            if stepped:
                rec.steps += 1
                rec.last_used = time.monotonic()
                self.steps_total += 1

    # -- the device path: gather / scatter -----------------------------

    def gather(self, slots):
        """Dense ``(occupancy,) + row_shape`` block per state tensor
        for the given slot indices — XLA gathers over the pool, so the
        results are computation outputs (donation-safe into the step
        executable without laundering)."""
        import jax.numpy as jnp

        idx = jnp.asarray(onp.asarray(slots, onp.int32))
        with self._lock:
            pools = list(self._pools)
        return [pool[idx] for pool in pools]

    def scatter(self, slots, new_states):
        """Write a step's output states back into the pool rows."""
        idx = onp.asarray(slots, onp.int32)
        import jax.numpy as jnp

        jidx = jnp.asarray(idx)
        with self._lock:
            for i, ns in enumerate(new_states):
                self._pools[i] = self._pools[i].at[jidx].set(ns)

    def read(self, sid):
        """Host copies of one session's state rows (tests, export)."""
        with self._lock:
            rec = self._slots.get(sid)
            if rec is None:
                raise MXNetError(f"unknown session {sid!r}")
            return [onp.asarray(pool[rec.slot]) for pool in self._pools]

    # -- checkpoint / migration ----------------------------------------

    def export_state(self):
        """Host snapshot of every live session — the payload the
        round-12 ``CheckpointManager`` rides (``session_state=``) and
        a canary promote migrates. Pure host primitives, so it pickles
        under the manifest's content hashes unchanged."""
        with self._lock:
            recs = list(self._slots.values())
            pools = list(self._pools)
        sessions = {}
        for rec in recs:
            sessions[rec.sid] = {
                "steps": rec.steps,
                "states": [onp.asarray(pool[rec.slot])
                           for pool in pools]}
        return {"format": 1,
                "state_shapes": [list(s) for s in self.state_shapes],
                "state_dtypes": [str(dt) for dt in self.state_dtypes],
                "sessions": sessions}

    def restore_state(self, payload):
        """Re-open every session in an :meth:`export_state` payload
        (checkpoint restore, or live migration at canary promote).
        Returns the number of sessions resumed; each bumps the
        ``resumed_sessions`` counter. A shape/dtype mismatch raises —
        resuming garbage into the pool would serve silent corruption."""
        if payload is None:
            return 0
        shapes = tuple(tuple(s) for s in payload.get("state_shapes", ()))
        if shapes != self.state_shapes:
            raise MXNetError(
                f"session-state payload shapes {shapes} do not match "
                f"this store's {self.state_shapes}; cannot resume")
        restored = 0
        for sid, ent in payload.get("sessions", {}).items():
            with self._lock:
                if not self._free and sid not in self._slots:
                    self._reclaim_locked()
                if not self._free and sid not in self._slots:
                    logging.warning(
                        "serving: session-state restore ran out of "
                        "slots; %s (and later sessions) not resumed",
                        sid)
                    break
                self.open(sid, init_states=ent["states"], _resumed=True)
                self._slots[sid].steps = int(ent.get("steps", 0))
            restored += 1
        return restored

    def close(self):
        """Unregister the occupancy probe (the pool itself is freed by
        refcount)."""
        METRICS.unregister_occupancy_probe(self._occupancy_token)

    def __repr__(self):
        return (f"SessionStateStore(slots={self.num_slots}, "
                f"live={self.occupancy}, "
                f"bytes_per_session={self.bytes_per_session}, "
                f"ttl_s={self.ttl_s:g})")
