"""Server-side session state: the memory of incremental decode.

Stateless serving re-executes a sequence's whole prefix on every token
— O(prefix) work per step. This module keeps each client's recurrent /
KV state ON THE SERVER, in a preallocated device-resident pool, so a
decode step costs exactly one cell forward regardless of position
(the continuous-batching literature's KV-cache discipline applied to
the round-10 serving stack).

:class:`SessionStateStore` holds one **slot** per live session: for
every state tensor the model threads, the store owns a device array of
shape ``(num_slots,) + row_shape`` allocated once at construction.
Sessions are *slot-indexed*, not shape-indexed — a decode batch gathers
whichever slots are live into a dense ``(occupancy, ...)`` block, runs
ONE compiled step executable, and scatters the new state back — so a
single AOT program serves any batch membership, exactly the bucketing
discipline the rest of the stack lives by.

**Paged KV storage (round 21).** Worst-case-length slots are the wrong
shape for transformer decode: a (max_len, embed) KV cache reserves
max_len bytes the moment a stream opens, even while it sits at token 3.
When ``MXNET_SERVING_STATE_PAGE_TOKENS`` is set (> 0) and the model
marks cache rows *pageable* (``state_row_pageable()``), those rows are
stored as fixed-size token pages in a shared page pool — the vLLM
discipline: each session keeps a small page TABLE (logical page →
physical page), pages are allocated lazily as the stream crosses page
boundaries, and physical page 0 is the reserved **null page** (always
zeros, never written), so unallocated table entries gather as zeros and
the decode attention mask keeps them inert. The compiled step never
changes shape — gather materializes the same dense
``(occupancy, max_len, ...)`` block from pages, and scatter writes back
only the ONE page the step touched (valid because the decode cache
contract is append-only: ``_cache_append`` is an exact scatter at the
step position, bitwise transparent to every other entry). The same
``MXNET_SERVING_STATE_BUDGET_MB`` therefore admits several× more
concurrent mixed-length streams. ``MXNET_SERVING_STATE_KV_INT8``
additionally stores fp32 pages as symmetric per-page int8 (+ one fp32
scale per page, via the round-19 quantize lattice helpers) — half the
page bytes again, opt-in and accuracy-gated by the caller.

Policies:

- **Affinity** — a session's steps never interleave: the store marks a
  slot ``in_flight`` while a step batch holds it, the continuous
  batcher admits at most one queued step per session into a batch, and
  eviction never touches an in-flight slot.
- **TTL + LRU under a byte budget** — the pool is sized by
  ``MXNET_SERVING_STATE_SLOTS`` capped by
  ``MXNET_SERVING_STATE_BUDGET_MB``; opening a session when every slot
  is taken first reclaims idle-expired sessions
  (``MXNET_SERVING_STATE_TTL_S``), then the least-recently-stepped one.
  Page exhaustion reclaims the same way — TTL first, then whole LRU
  sessions (page granularity never splits a victim: evicting one
  session frees ALL its pages and nothing of anyone else's, the
  blast-radius contract). An evicted session's next step raises
  :class:`SessionEvicted` — a clean, retryable 503 telling exactly
  that one client to re-open.
- **Checkpointable** — :meth:`export_state` / :meth:`restore_state`
  round-trip every live session as host arrays; the round-12
  ``CheckpointManager(session_state=store)`` rides them in its
  manifest-hashed payload, and a round-13 canary promote migrates live
  sessions into the new version's store instead of dropping them
  (``resumed_sessions`` counts both paths). Payload states are always
  DENSE rows regardless of page geometry, so a checkpoint taken under
  one ``PAGE_TOKENS`` restores under another (or under row-slot mode)
  unchanged.

The ``session_state_evict`` fault seam fires in :meth:`acquire` —
chaos drills can evict any session mid-stream and assert the blast
radius is one client. Page allocation is wrapped in a
``serving.page_alloc`` telemetry span.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, deque

import numpy as onp

from ..base import MXNetError
from ..utils import locks as _locks
from .batcher import ServerBusy
from .metrics import METRICS

__all__ = ["SessionStateStore", "SessionEvicted"]

#: evicted-session tombstones kept for clean error reporting; past the
#: bound the oldest fold into the generic "unknown session" error
_TOMBSTONES = 4096


# ---------------------------------------------------------------------------
# fused paged-pool kernels
#
# The paged gather/scatter paths are on the per-step critical path: run
# eagerly they cost ~5 dispatches per pageable tensor (reshape, arange
# page-pick, quantize, indexed set, ...) vs the row-slot path's one,
# which at high occupancy dominates the decode step itself. Each helper
# fuses its whole read/write into a single jitted call, cached per pool
# geometry (and retraced per occupancy, which is bounded by the
# batcher's size ladder). None of these donate their pool operand:
# gather() hands out pool references that are indexed OUTSIDE the store
# lock, so an aliased update could race a concurrent reader.

def _lru(fn):
    import functools

    return functools.lru_cache(maxsize=None)(fn)


@_lru
def _paged_gather_fn(seq, tail):
    from ..utils.compile_cache import counting_jit

    def fn(pool, tables):
        return pool[tables].reshape((tables.shape[0], seq) + tail)

    return counting_jit(fn, label="paged_gather")


@_lru
def _paged_gather_int8_fn(seq, tail):
    from ..analysis.quantize import dequantize_kv_pages
    from ..utils.compile_cache import counting_jit

    def fn(pool, scales, tables):
        pg = dequantize_kv_pages(pool[tables], scales[tables])
        return pg.reshape((tables.shape[0], seq) + tail)

    return counting_jit(fn, label="paged_gather_int8")


@_lru
def _paged_scatter_fn(ppr, page_tokens, tail):
    import jax.numpy as jnp

    from ..utils.compile_cache import counting_jit

    def fn(pool, ns, pidx, dest):
        nr = ns.reshape((ns.shape[0], ppr, page_tokens) + tail)
        changed = nr[jnp.arange(ns.shape[0]), pidx]
        return pool.at[dest].set(changed)

    return counting_jit(fn, label="paged_scatter")


@_lru
def _paged_scatter_int8_fn(ppr, page_tokens, tail):
    import jax.numpy as jnp

    from ..analysis.quantize import kv_page_codes
    from ..utils.compile_cache import counting_jit

    def fn(pool, scales, ns, pidx, dest):
        nr = ns.reshape((ns.shape[0], ppr, page_tokens) + tail)
        q, sc = kv_page_codes(nr[jnp.arange(ns.shape[0]), pidx])
        return pool.at[dest].set(q), scales.at[dest].set(sc)

    return counting_jit(fn, label="paged_scatter_int8")


class SessionEvicted(ServerBusy):
    """This session's server-side state slot was reclaimed (idle TTL,
    LRU pressure under the byte budget, or an injected fault) — the
    stream cannot continue from server state. Retryable: re-open the
    session (optionally from a checkpoint) and resume. Maps to HTTP
    503 with a Retry-After hint, and is delivered to exactly the one
    client whose slot went away."""


class _Slot:
    """One live session's bookkeeping (state lives in the pool).
    ``table`` (paged stores only) maps logical page index → physical
    page, 0 = the null page; ``steps`` doubles as the token count for
    page math — a decode step appends exactly one token."""

    __slots__ = ("sid", "slot", "created", "last_used", "steps",
                 "in_flight", "table")

    def __init__(self, sid, slot, now):
        self.sid = sid
        self.slot = slot
        self.created = now
        self.last_used = now
        self.steps = 0
        self.in_flight = False
        self.table = None


class SessionStateStore:
    """Slot-indexed, device-resident per-session state pool.

    Parameters
    ----------
    state_shapes : sequence of shape tuples
        Per-state ROW shapes (no batch axis), e.g. ``[(256,), (256,)]``
        for an LSTM — ``RecurrentCell.state_row_shapes()`` emits them.
    state_dtypes : sequence of dtypes, optional (default float32)
    max_sessions : int, optional — slot count before the byte budget
        (default ``MXNET_SERVING_STATE_SLOTS``)
    byte_budget : int, optional — pool byte cap; shrinks the slot
        count to fit (default ``MXNET_SERVING_STATE_BUDGET_MB`` MiB)
    ttl_s : float, optional — idle expiry (default
        ``MXNET_SERVING_STATE_TTL_S``); <= 0 disables
    pageable : sequence of bool, optional — which state rows grow along
        a leading token axis (``state_row_pageable()``); those are
        stored as fixed-size pages when ``page_tokens`` > 0
    page_tokens : int, optional — tokens per KV page (default
        ``MXNET_SERVING_STATE_PAGE_TOKENS``); 0 = row-slot mode
    kv_int8 : bool, optional — store fp32 pages as symmetric per-page
        int8 (default ``MXNET_SERVING_STATE_KV_INT8``)
    label : str, optional — logging/debug tag
    """

    def __init__(self, state_shapes, state_dtypes=None, max_sessions=None,
                 byte_budget=None, ttl_s=None, pageable=None,
                 page_tokens=None, kv_int8=None, label=None):
        import jax.numpy as jnp

        from .. import env as _env

        self.label = label
        self.state_shapes = tuple(tuple(int(d) for d in s)
                                  for s in state_shapes)
        if not self.state_shapes:
            raise MXNetError("state_shapes must name at least one "
                             "state tensor")
        dts = state_dtypes or ["float32"] * len(self.state_shapes)
        if len(dts) != len(self.state_shapes):
            raise MXNetError("state_dtypes length must match "
                             "state_shapes")
        self.state_dtypes = tuple(onp.dtype(d) for d in dts)
        self.bytes_per_session = int(sum(
            int(onp.prod(s or (1,))) * dt.itemsize
            for s, dt in zip(self.state_shapes, self.state_dtypes)))

        # -- page geometry (round 21) ---------------------------------
        self.page_tokens = int(
            page_tokens if page_tokens is not None else
            _env.get_int("MXNET_SERVING_STATE_PAGE_TOKENS", 0))
        flags = tuple(bool(p) for p in pageable) if pageable else \
            (False,) * len(self.state_shapes)
        if len(flags) != len(self.state_shapes):
            raise MXNetError("pageable length must match state_shapes")
        self._pageable = flags if self.page_tokens > 0 else \
            (False,) * len(self.state_shapes)
        self.paged = any(self._pageable)
        self.kv_int8 = bool(
            kv_int8 if kv_int8 is not None else
            _env.get_bool("MXNET_SERVING_STATE_KV_INT8", False)) \
            and self.paged
        if self.paged:
            seqs = {self.state_shapes[i][0] if self.state_shapes[i]
                    else 0 for i, p in enumerate(self._pageable) if p}
            if len(seqs) != 1:
                raise MXNetError(
                    "pageable state rows must share one leading token "
                    f"axis; got lengths {sorted(seqs)}")
            self._seq = seqs.pop()
            if self._seq <= 0 or self._seq % self.page_tokens:
                raise MXNetError(
                    f"pageable token axis {self._seq} must be a "
                    f"positive multiple of page_tokens "
                    f"{self.page_tokens}")
            self._ppr = self._seq // self.page_tokens  # pages per row
        else:
            self._seq = 0
            self._ppr = 0
        # int8 page storage only applies to float32 pageable rows
        self._int8 = tuple(
            self.kv_int8 and p and dt == onp.dtype("float32")
            for p, dt in zip(self._pageable, self.state_dtypes))

        #: bytes one physical page costs across every pageable pool
        #: (int8 pages carry one fp32 scale each)
        self._page_bytes = int(sum(
            self.page_tokens * int(onp.prod(s[1:] or (1,)))
            * (1 if i8 else dt.itemsize) + (4 if i8 else 0)
            for s, dt, p, i8 in zip(self.state_shapes, self.state_dtypes,
                                    self._pageable, self._int8) if p))
        #: bytes one slot costs in the non-pageable pools
        self._slot_bytes = int(sum(
            int(onp.prod(s or (1,))) * dt.itemsize
            for s, dt, p in zip(self.state_shapes, self.state_dtypes,
                                self._pageable) if not p))

        slots = int(max_sessions if max_sessions is not None else
                    _env.get_int("MXNET_SERVING_STATE_SLOTS", 64))
        budget = int(byte_budget if byte_budget is not None else
                     _env.get_int("MXNET_SERVING_STATE_BUDGET_MB", 64)
                     * 1024 * 1024)
        if budget > 0:
            if self.paged:
                # a live stream costs its slot rows + at least one page
                slots = min(slots, max(
                    budget // max(self._slot_bytes + self._page_bytes, 1),
                    1))
            else:
                slots = min(slots, max(budget // self.bytes_per_session,
                                       1))
        self.num_slots = max(slots, 1)
        if self.paged:
            pages = ((budget - self.num_slots * self._slot_bytes)
                     // max(self._page_bytes, 1)) if budget > 0 else \
                self.num_slots * self._ppr
            self.num_pages = max(min(int(pages),
                                     self.num_slots * self._ppr), 1)
        else:
            self.num_pages = 0
        self.ttl_s = float(ttl_s if ttl_s is not None else
                           _env.get_float("MXNET_SERVING_STATE_TTL_S",
                                          600.0))
        # the pools: ONE preallocated device array per state tensor —
        # gather/scatter are XLA ops over it, never per-session
        # uploads. Pageable tensors are page-indexed (physical page 0
        # = the reserved null page, kept all-zeros); the rest are
        # slot-indexed as before.
        self._pools = []
        self._scales = []
        for i, (s, dt) in enumerate(zip(self.state_shapes,
                                        self.state_dtypes)):
            if self._pageable[i]:
                pdt = "int8" if self._int8[i] else str(dt)
                self._pools.append(jnp.zeros(
                    (self.num_pages + 1, self.page_tokens) + s[1:],
                    dtype=pdt))
                self._scales.append(
                    jnp.zeros((self.num_pages + 1,), dtype="float32")
                    if self._int8[i] else None)
            else:
                self._pools.append(jnp.zeros((self.num_slots,) + s,
                                             dtype=str(dt)))
                self._scales.append(None)
        # guards: _slots, _free, _free_pages, _evicted, steps_total
        self._lock = _locks.RankedRLock("serving.store")
        self._slots = OrderedDict()  # sid -> _Slot, LRU order
        self._free = list(range(self.num_slots - 1, -1, -1))
        # physical pages 1..num_pages (0 is the null page)
        self._free_pages = list(range(self.num_pages, 0, -1))
        self._evicted = OrderedDict()  # sid -> reason (tombstones)
        self.steps_total = 0
        self._occupancy_token = METRICS.register_occupancy_probe(
            lambda: len(self._slots))
        self._page_token = METRICS.register_page_probe(
            self._page_probe) if self.paged else None

    # -- introspection -------------------------------------------------

    @property
    def occupancy(self):
        with self._lock:
            return len(self._slots)

    def has(self, sid):
        with self._lock:
            return sid in self._slots

    def live_sessions(self):
        with self._lock:
            return list(self._slots)

    def stats(self):
        """Flat description for /healthz and admission probes."""
        with self._lock:
            st = {"sessions": len(self._slots),
                  "slots": self.num_slots,
                  "bytes_per_session": self.bytes_per_session,
                  "ttl_s": self.ttl_s,
                  "steps_total": self.steps_total}
            if self.paged:
                st.update({
                    "page_tokens": self.page_tokens,
                    "pages_total": self.num_pages,
                    "pages_free": len(self._free_pages),
                    "pages_used": self.num_pages - len(self._free_pages),
                    "page_bytes": self._page_bytes,
                    "kv_int8": self.kv_int8})
            return st

    def page_headroom(self):
        """Free fraction of the KV page pool, 0..1 (``None`` in
        row-slot mode) — admission folds it like slot headroom."""
        if not self.paged:
            return None
        with self._lock:
            return len(self._free_pages) / max(self.num_pages, 1)

    def _page_probe(self):
        """Page-pool gauge sample for the metrics registry."""
        with self._lock:
            used = self.num_pages - len(self._free_pages)
            per = [int(onp.count_nonzero(r.table))
                   for r in self._slots.values() if r.table is not None]
        return {"pages_total": self.num_pages, "pages_used": used,
                "pages_per_session": per,
                "kv_bytes": used * self._page_bytes}

    # -- lifecycle -----------------------------------------------------

    def open(self, sid, init_states=None, _resumed=False, tokens=None):
        """Allocate (or return) the state slot for ``sid``. A fresh
        slot starts at zeros unless ``init_states`` (per-state ROW
        arrays, always DENSE regardless of page geometry) seeds it.
        ``tokens`` bounds how many leading positions of pageable rows
        are live (restore passes the session's step count); ``None``
        materializes every page — safe, never lossy. Reclaims
        TTL-expired then LRU slots when full; raises
        :class:`ServerBusy` only when every slot (or page) is pinned
        by an in-flight step batch. Idempotent for an already open
        session (``init_states`` then rewrites its state)."""
        import jax.numpy as jnp

        sid = str(sid)
        with self._lock:
            rec = self._slots.get(sid)
            if rec is None:
                if not self._free:
                    self._reclaim_locked()
                if not self._free:
                    raise ServerBusy(
                        f"no free session-state slot ({self.num_slots} "
                        "slots, all in flight); retry later")
                rec = _Slot(sid, self._free.pop(), time.monotonic())
                if self.paged:
                    rec.table = onp.zeros(self._ppr, dtype=onp.int32)
                self._slots[sid] = rec
                self._evicted.pop(sid, None)
                # a reused slot still holds the previous tenant's
                # state: reset it (zeros) or seed it before anyone
                # gathers (pageable rows need nothing — a fresh table
                # is all null pages, which gather as zeros)
                if init_states is None:
                    for i, pool in enumerate(self._pools):
                        if not self._pageable[i]:
                            self._pools[i] = pool.at[rec.slot].set(0)
            if init_states is not None:
                if len(init_states) != len(self._pools):
                    raise MXNetError(
                        f"expected {len(self._pools)} state tensor(s), "
                        f"got {len(init_states)}")
                rows = []
                for i, s in enumerate(init_states):
                    row = onp.asarray(s, dtype=self.state_dtypes[i])
                    if tuple(row.shape) != self.state_shapes[i]:
                        raise MXNetError(
                            f"state {i} row shape {tuple(row.shape)} "
                            f"!= expected {self.state_shapes[i]}")
                    rows.append(row)
                if self.paged:
                    t = self._seq if tokens is None else \
                        max(0, min(int(tokens), self._seq))
                    npages = -(-t // self.page_tokens) if t else 0
                    self._release_pages_locked(rec)
                    self._alloc_pages_locked(rec, npages)
                for i, row in enumerate(rows):
                    if self._pageable[i]:
                        if npages:
                            rr = row.reshape(
                                (self._ppr, self.page_tokens)
                                + self.state_shapes[i][1:])
                            dest = jnp.asarray(
                                rec.table[:npages].copy())
                            pages = jnp.asarray(rr[:npages])
                            if self._int8[i]:
                                from ..analysis.quantize import \
                                    quantize_kv_page
                                q, sc = quantize_kv_page(pages)
                                self._pools[i] = \
                                    self._pools[i].at[dest].set(q)
                                self._scales[i] = \
                                    self._scales[i].at[dest].set(sc)
                            else:
                                self._pools[i] = \
                                    self._pools[i].at[dest].set(pages)
                    else:
                        self._pools[i] = self._pools[i].at[
                            rec.slot].set(jnp.asarray(row))
            if _resumed:
                METRICS.bump("resumed_sessions")
            return rec.slot

    def open_for_step(self, sid):
        """The batcher's IMPLICIT open — a stream's first step
        allocates its slot on arrival. Unlike :meth:`open` (the
        explicit client re-open, which clears any tombstone), this
        refuses evicted sessions: a pipelined stream whose slot went
        away must see :class:`SessionEvicted` on every remaining step,
        never a silent restart from zero state."""
        with self._lock:
            if sid not in self._slots:
                reason = self._evicted.get(sid)
                if reason is not None:
                    raise SessionEvicted(
                        f"session {sid!r} state was evicted ({reason}); "
                        "re-open the session and retry")
            return self.open(sid)

    def _reclaim_locked(self):
        """Refill ``_free`` by one slot: TTL-expired sessions first
        (all of them — they are dead weight), then the LRU session.
        In-flight slots are never reclaimed (affinity)."""
        now = time.monotonic()
        if self.ttl_s > 0:
            for sid in [s for s, r in self._slots.items()
                        if not r.in_flight and
                        now - r.last_used > self.ttl_s]:
                self._evict_locked(sid, "idle TTL expired")
        if self._free:
            return
        for sid, rec in self._slots.items():  # OrderedDict = LRU order
            if not rec.in_flight:
                self._evict_locked(sid, "LRU pressure (pool full)")
                return

    def _reclaim_pages_locked(self, needed, exclude=None):
        """Refill ``_free_pages`` to ``needed``: TTL-expired sessions
        first, then whole LRU sessions — page reclamation NEVER splits
        a victim (evicting one session frees all of its pages and
        touches nobody else, the blast-radius contract). In-flight
        sessions and ``exclude`` (the allocating session itself) are
        never victims."""
        now = time.monotonic()
        if self.ttl_s > 0:
            for sid in [s for s, r in self._slots.items()
                        if not r.in_flight and s != exclude and
                        now - r.last_used > self.ttl_s]:
                self._evict_locked(sid, "idle TTL expired")
        while len(self._free_pages) < needed:
            victim = next(
                (s for s, r in self._slots.items()
                 if not r.in_flight and s != exclude), None)
            if victim is None:
                return
            self._evict_locked(victim, "LRU page pressure (pool full)")

    def _release_pages_locked(self, rec):
        """Return every physical page in ``rec``'s table to the free
        list (content is zeroed lazily at the next allocation)."""
        if rec.table is None:
            return
        for p in rec.table:
            if p:
                self._free_pages.append(int(p))
        rec.table[:] = 0

    def _alloc_pages_locked(self, rec, npages):
        """Back logical pages ``0..npages-1`` of ``rec`` with physical
        pages, reclaiming (TTL → whole LRU sessions) on exhaustion;
        raises :class:`ServerBusy` when the pool genuinely cannot
        supply them. Fresh pages are zeroed in every pageable pool —
        a recycled page must never leak the previous tenant's KV."""
        missing = [j for j in range(npages) if not rec.table[j]]
        if not missing:
            return
        from ..telemetry import tracer as _telem

        with _telem.span("serving.page_alloc", cat="serving",
                         sid=rec.sid, pages=len(missing)):
            with self._lock:
                if len(self._free_pages) < len(missing):
                    self._reclaim_pages_locked(len(missing),
                                               exclude=rec.sid)
                if len(self._free_pages) < len(missing):
                    raise ServerBusy(
                        f"no free KV pages ({self.num_pages} pages, "
                        f"{len(self._free_pages)} free, "
                        f"{len(missing)} needed; every other stream "
                        "is in flight); retry later")
                got = [self._free_pages.pop() for _ in missing]
                for j, p in zip(missing, got):
                    rec.table[j] = p
                dest = None
                for i, pool in enumerate(self._pools):
                    if not self._pageable[i]:
                        continue
                    import jax.numpy as jnp

                    if dest is None:
                        dest = jnp.asarray(onp.asarray(got, onp.int32))
                    self._pools[i] = pool.at[dest].set(0)
                    if self._scales[i] is not None:
                        self._scales[i] = \
                            self._scales[i].at[dest].set(0.0)

    def _evict_locked(self, sid, reason):
        rec = self._slots.pop(sid)
        self._free.append(rec.slot)
        self._release_pages_locked(rec)
        self._evicted[sid] = reason
        while len(self._evicted) > _TOMBSTONES:
            self._evicted.popitem(last=False)
        METRICS.bump("evictions")
        logging.info("serving%s: session %s evicted after %d step(s): "
                     "%s", f" {self.label}" if self.label else "", sid,
                     rec.steps, reason)

    def evict(self, sid, reason="operator request"):
        """Explicitly drop one session's state (no-op if unknown)."""
        with self._lock:
            if sid in self._slots:
                self._evict_locked(sid, reason)

    def acquire(self, sid):
        """Pin ``sid``'s slot for one decode step; returns the slot
        record. The ``session_state_evict`` fault seam fires here —
        an injected fire evicts THIS session and raises
        :class:`SessionEvicted`, so chaos drills hit exactly one
        client. TTL expiry is also enforced here (the lazy half of
        reclamation), and a paged store ensures the page this step
        writes into is backed — which may evict an idle LRU session,
        or raise retryable :class:`ServerBusy` when the page pool is
        truly pinned. Pair with :meth:`release`."""
        from ..resilience import faults as _faults
        from ..resilience.faults import InjectedFault

        with self._lock:
            rec = self._slots.get(sid)
            if rec is None:
                reason = self._evicted.get(sid)
                if reason is not None:
                    raise SessionEvicted(
                        f"session {sid!r} state was evicted ({reason}); "
                        "re-open the session and retry")
                raise MXNetError(
                    f"unknown session {sid!r} (never opened on this "
                    "server)")
            if rec.in_flight:
                raise MXNetError(
                    f"session {sid!r} already has a step in flight "
                    "(affinity violation — one step at a time)")
            try:
                _faults.maybe_fail("session_state_evict")
            except InjectedFault as e:
                self._evict_locked(sid, f"injected fault ({e})")
                raise SessionEvicted(
                    f"session {sid!r} state was evicted (injected "
                    "fault); re-open the session and retry") from e
            now = time.monotonic()
            if self.ttl_s > 0 and now - rec.last_used > self.ttl_s:
                self._evict_locked(sid, "idle TTL expired")
                raise SessionEvicted(
                    f"session {sid!r} state expired after "
                    f"{self.ttl_s:g}s idle; re-open the session and "
                    "retry")
            if self.paged:
                # this step appends token ``steps``: back its page
                pidx = min(rec.steps // self.page_tokens,
                           self._ppr - 1)
                self._alloc_pages_locked(rec, pidx + 1)
            rec.in_flight = True
            rec.last_used = now
            self._slots.move_to_end(sid)
            return rec

    def release(self, rec, stepped=True):
        """Unpin a slot after its step batch resolves."""
        with self._lock:
            rec.in_flight = False
            if stepped:
                rec.steps += 1
                rec.last_used = time.monotonic()
                self.steps_total += 1

    # -- the device path: gather / scatter -----------------------------

    def _resolve_locked(self, items):
        """Normalize a gather/scatter membership list — slot records
        (the batcher's currency) or raw slot indices (tests, the
        row-slot legacy call shape) — to slot records."""
        recs = []
        by_slot = None
        for it in items:
            if isinstance(it, _Slot):
                recs.append(it)
                continue
            if by_slot is None:
                by_slot = {r.slot: r for r in self._slots.values()}
            rec = by_slot.get(int(it))
            if rec is None:
                raise MXNetError(
                    f"slot {int(it)} does not belong to a live "
                    "session")
            recs.append(rec)
        return recs

    def gather(self, slots):
        """Dense ``(occupancy,) + row_shape`` block per state tensor
        for the given slot records (or indices) — XLA gathers over the
        pool, so the results are computation outputs (donation-safe
        into the step executable without laundering). Pageable tensors
        materialize through each session's page table: unallocated
        entries hit the null page and gather as exact zeros."""
        import jax.numpy as jnp

        with self._lock:
            recs = self._resolve_locked(slots)
            pools = list(self._pools)
            scales = list(self._scales)
            idx = jnp.asarray(onp.asarray([r.slot for r in recs],
                                          onp.int32))
            tables = jnp.asarray(onp.stack(
                [r.table for r in recs]).astype(onp.int32)) \
                if self.paged else None
        outs = []
        for i, pool in enumerate(pools):
            if self._pageable[i]:
                tail = self.state_shapes[i][1:]
                if self._int8[i]:
                    outs.append(_paged_gather_int8_fn(self._seq, tail)(
                        pool, scales[i], tables))
                else:
                    outs.append(_paged_gather_fn(self._seq, tail)(
                        pool, tables))
            else:
                outs.append(pool[idx])
        return outs

    def scatter(self, slots, new_states):
        """Write a step's output states back into the pool rows. A
        paged tensor writes back ONLY the page this step appended into
        (``_cache_append`` is an exact scatter at the step position,
        so every other page of the step's output is bitwise the page
        content that was gathered — rewriting it would be a no-op, or
        worse for int8, a fresh requantization of untouched data)."""
        import jax.numpy as jnp

        with self._lock:
            recs = self._resolve_locked(slots)
            jidx = jnp.asarray(onp.asarray([r.slot for r in recs],
                                           onp.int32))
            if self.paged:
                pidx = onp.asarray(
                    [min(r.steps // self.page_tokens, self._ppr - 1)
                     for r in recs], onp.int32)
                dest = onp.asarray(
                    [int(r.table[p]) for r, p in zip(recs, pidx)],
                    onp.int32)
                if not dest.all():
                    raise MXNetError(
                        "scatter into an unbacked KV page (acquire() "
                        "must precede the step that appends)")
                jdest = jnp.asarray(dest)
                jpidx = jnp.asarray(pidx)
            for i, ns in enumerate(new_states):
                if self._pageable[i]:
                    ns = jnp.asarray(ns)
                    tail = self.state_shapes[i][1:]
                    if self._int8[i]:
                        self._pools[i], self._scales[i] = \
                            _paged_scatter_int8_fn(
                                self._ppr, self.page_tokens, tail)(
                                self._pools[i], self._scales[i],
                                ns, jpidx, jdest)
                        from ..analysis import quantize as _q

                        _q._count("kv_pages_quantized", len(recs))
                    else:
                        self._pools[i] = _paged_scatter_fn(
                            self._ppr, self.page_tokens, tail)(
                            self._pools[i], ns, jpidx, jdest)
                else:
                    self._pools[i] = self._pools[i].at[jidx].set(ns)

    def _dense_rows(self, rec, pools, scales):
        """Host copies of one session's state rows, densified through
        its page table (the read/export representation is ALWAYS the
        dense row, whatever the storage geometry)."""
        import jax.numpy as jnp

        rows = []
        for i, pool in enumerate(pools):
            if self._pageable[i]:
                t = jnp.asarray(rec.table.astype(onp.int32))
                pg = pool[t]
                if self._int8[i]:
                    from ..analysis.quantize import dequantize_kv_pages

                    pg = dequantize_kv_pages(pg, scales[i][t])
                rows.append(onp.asarray(pg.reshape(
                    (self._seq,) + self.state_shapes[i][1:])))
            else:
                rows.append(onp.asarray(pool[rec.slot]))
        return rows

    def read(self, sid):
        """Host copies of one session's state rows (tests, export)."""
        with self._lock:
            rec = self._slots.get(sid)
            if rec is None:
                raise MXNetError(f"unknown session {sid!r}")
            pools = list(self._pools)
            scales = list(self._scales)
        return self._dense_rows(rec, pools, scales)

    # -- checkpoint / migration ----------------------------------------

    def export_state(self):
        """Host snapshot of every live session — the payload the
        round-12 ``CheckpointManager`` rides (``session_state=``) and
        a canary promote migrates. Pure host primitives, so it pickles
        under the manifest's content hashes unchanged. States are
        DENSE rows whatever the page geometry, so the payload restores
        across ``PAGE_TOKENS``/int8 flips and into row-slot stores."""
        with self._lock:
            recs = list(self._slots.values())
            pools = list(self._pools)
            scales = list(self._scales)
        sessions = {}
        for rec in recs:
            sessions[rec.sid] = {
                "steps": rec.steps,
                "states": self._dense_rows(rec, pools, scales)}
        return {"format": 1,
                "state_shapes": [list(s) for s in self.state_shapes],
                "state_dtypes": [str(dt) for dt in self.state_dtypes],
                "sessions": sessions}

    def restore_state(self, payload):
        """Re-open every session in an :meth:`export_state` payload
        (checkpoint restore, or live migration at canary promote).
        Returns the number of sessions resumed; each bumps the
        ``resumed_sessions`` counter. A shape/dtype mismatch raises —
        resuming garbage into the pool would serve silent corruption.
        The session's step count bounds page materialization in a
        paged store (a decode step is one token), so short streams
        resume into few pages."""
        if payload is None:
            return 0
        shapes = tuple(tuple(s) for s in payload.get("state_shapes", ()))
        if shapes != self.state_shapes:
            raise MXNetError(
                f"session-state payload shapes {shapes} do not match "
                f"this store's {self.state_shapes}; cannot resume")
        restored = 0
        for sid, ent in payload.get("sessions", {}).items():
            with self._lock:
                if not self._free and sid not in self._slots:
                    self._reclaim_locked()
                if not self._free and sid not in self._slots:
                    logging.warning(
                        "serving: session-state restore ran out of "
                        "slots; %s (and later sessions) not resumed",
                        sid)
                    break
                try:
                    self.open(sid, init_states=ent["states"],
                              _resumed=True,
                              tokens=ent.get("steps"))
                except ServerBusy:
                    logging.warning(
                        "serving: session-state restore ran out of KV "
                        "pages; %s (and later sessions) not resumed",
                        sid)
                    break
                self._slots[sid].steps = int(ent.get("steps", 0))
            restored += 1
        return restored

    def close(self):
        """Unregister the metrics probes (the pools themselves are
        freed by refcount)."""
        METRICS.unregister_occupancy_probe(self._occupancy_token)
        if self._page_token is not None:
            METRICS.unregister_page_probe(self._page_token)

    def __repr__(self):
        paged = (f", page_tokens={self.page_tokens}, "
                 f"pages={self.num_pages}"
                 + (", kv_int8" if self.kv_int8 else "")) \
            if self.paged else ""
        return (f"SessionStateStore(slots={self.num_slots}, "
                f"live={self.occupancy}, "
                f"bytes_per_session={self.bytes_per_session}, "
                f"ttl_s={self.ttl_s:g}{paged})")


# -- artifact-layer salt provider -------------------------------------------

def fingerprint_salt(ctx):
    """Compile-cache salt for decode-step executables served out of a
    PAGED state store: page geometry and int8-KV storage are serving-
    tier knobs that must re-key bundled step artifacts (a fleet
    replica resolving a bundle compiled under different KV plumbing
    must miss, not collide). Row-slot sessions — and every stateless
    artifact — contribute nothing, which keeps all pre-existing cache
    keys stable."""
    if not ctx.get("paged"):
        return ()
    return ("paged_state", int(ctx.get("page_tokens", 0)),
            bool(ctx.get("kv_int8", False)))


def _salt_provider(ctx):
    return fingerprint_salt(ctx)


from ..artifact import salts as _artifact_salts  # noqa: E402

_artifact_salts.register_salt_provider("paged_state", _salt_provider)
