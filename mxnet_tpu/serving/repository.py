"""Multi-model repository: N models x versions, canary rollout,
auto-rollback.

The model-management layer of the serving subsystem (reference analog:
MXNet Model Server's model store — register/serve N models, roll
versions without dropping traffic). Each (model, version) owns its own
:class:`~mxnet_tpu.serving.batcher.DynamicBatcher`, so tenants never
share a coalescing queue and one model's overload can't starve
another's batches; the process-wide admission/metrics layer still sees
the union.

Version lifecycle
-----------------
``deploy(name, session)`` registers a version. The FIRST version of a
model activates immediately; later versions start as a **canary**: a
configurable slice of non-critical traffic (deterministic counter
routing — exactly ``fraction`` of eligible requests, no RNG flakes)
runs on the new version while the incumbent keeps the rest.
``critical``-class requests never ride a canary.

The rollback decision is wired through
:class:`~mxnet_tpu.resilience.breaker.CircuitBreaker` rather than a
parallel mechanism: every canary execution failure — and every
sustained latency regression vs the incumbent
(``MXNET_SERVING_CANARY_LATENCY_X``) — is ``record_failure()`` on the
canary's breaker; the breaker leaving "closed" IS the auto-rollback
trigger. A canary failure is transparent to the client: the request is
re-run on the incumbent (``canary_fallbacks``), so a bad rollout shows
up in metrics, not in user-facing errors. After
``MXNET_SERVING_CANARY_MIN_REQUESTS`` clean canary completions the
version auto-promotes via an atomic hot-swap (the ``model_swap``
fault seam; an injected fire aborts the swap and the incumbent stays
active — rollback itself is deliberately seam-free).

Round 19 adds the **shadow accuracy gate** for quantized rollouts:
with ``MXNET_QUANTIZE_SHADOW`` > 0, that fraction of canary requests
is ALSO run on the incumbent and the answers are diffed; a relative
deviation past ``MXNET_QUANTIZE_SHADOW_TOL`` feeds the same breaker.
An int8 canary that is fast but numerically wrong — invisible to both
the failure and latency checks — rolls back automatically, and the
client never sees it (shadow verdicts land after the answer).

Every transition (deploy/promote/rollback/swap) bumps a process
counter surfaced through ``profiler.serving_counters()``, Prometheus
``/metrics`` and the repository's ``healthz()`` block.

Stateful sessions (round 16): a request carrying a ``session_id`` is
PINNED to the incumbent — its recurrent/KV state lives in the
incumbent's :class:`~mxnet_tpu.serving.state.SessionStateStore`, and a
canary has no copy of it, so the canary slice only ever samples
stateless traffic. ``promote`` migrates the incumbent's live sessions
into the successor's store (``export_state``/``restore_state``) before
the pointer moves on, so a rollout completes with zero dropped
mid-stream decodes (``resumed_sessions`` counts them).
"""
from __future__ import annotations

import logging
import threading
import time

from ..base import MXNetError
from ..resilience import faults as _faults
from ..utils import locks as _locks
from ..resilience.breaker import CircuitBreaker
from .batcher import DynamicBatcher
from .metrics import METRICS, SLO_CLASSES

__all__ = ["ModelRepository"]

#: EMA smoothing for the incumbent/canary latency comparison
_LAT_ALPHA = 0.2
#: canary latency samples required before the regression check fires
_MIN_LAT_SAMPLES = 8


def _rel_deviation(a, b):
    """max |a-b| / max |b| across (possibly nested) outputs — the
    shadow-check distance between a canary answer and the incumbent's.
    Normalizing by the incumbent's max keeps the tolerance meaningful
    for logits near zero, where elementwise relative error explodes."""
    import numpy as onp

    if isinstance(a, (list, tuple)):
        if not isinstance(b, (list, tuple)) or len(a) != len(b):
            return float("inf")
        return max((_rel_deviation(x, y) for x, y in zip(a, b)),
                   default=0.0)
    a = onp.asarray(a.asnumpy() if hasattr(a, "asnumpy") else a,
                    dtype="float64")
    b = onp.asarray(b.asnumpy() if hasattr(b, "asnumpy") else b,
                    dtype="float64")
    if a.shape != b.shape:
        return float("inf")
    denom = max(float(onp.max(onp.abs(b))), 1e-12) if b.size else 1.0
    return float(onp.max(onp.abs(a - b))) / denom if a.size else 0.0


class _Version:
    __slots__ = ("version", "session", "batcher")

    def __init__(self, version, session, batcher):
        self.version = version
        self.session = session
        self.batcher = batcher


class _Model:
    """One named model: its versions, the active pointer, and live
    canary state. ``lock`` is an RLock — promotion runs from a worker
    callback that already holds it."""

    def __init__(self, name):
        self.name = name
        # guards: versions, active, canary, canary_fraction, canary_breaker, canary_successes
        self.lock = _locks.RankedRLock("repository.model")
        self.versions = {}  # version -> _Version
        self.active = None
        self.canary = None
        self.canary_fraction = 0.0
        self.canary_breaker = None
        self.canary_successes = 0
        self.canary_failures = 0
        self.canary_lat_ema = None
        self.incumbent_lat_ema = None
        self._tick = 0  # deterministic canary routing counter
        self._shadow_tick = 0  # deterministic shadow-check sampling
        self.state = "empty"
        self.last_transition = "created"


class ModelRepository:
    """Host N models x versions behind per-model dynamic batchers.

    ``batcher_kwargs`` (max_batch_size, max_latency_ms, ...) apply to
    every batcher the repository builds. The first model deployed
    becomes the default (the bare ``/predict`` route)."""

    def __init__(self, canary_fraction=None, canary_min_requests=None,
                 canary_threshold=None, canary_latency_x=None,
                 **batcher_kwargs):
        from .. import env as _env

        # guards: _models, _default, _closed
        self._lock = _locks.RankedLock("repository")
        self._models = {}
        self._default = None
        self._closed = False
        self._batcher_kwargs = dict(batcher_kwargs)
        self._canary_fraction = float(
            canary_fraction if canary_fraction is not None else
            _env.get_float("MXNET_SERVING_CANARY_FRACTION", 0.1))
        self._canary_min_requests = int(
            canary_min_requests if canary_min_requests is not None else
            _env.get_int("MXNET_SERVING_CANARY_MIN_REQUESTS", 50))
        self._canary_threshold = int(
            canary_threshold if canary_threshold is not None else
            _env.get_int("MXNET_SERVING_CANARY_THRESHOLD", 3))
        self._canary_latency_x = float(
            canary_latency_x if canary_latency_x is not None else
            _env.get_float("MXNET_SERVING_CANARY_LATENCY_X", 3.0))
        # shadow accuracy gate (round 19): a fraction of canary
        # requests ALSO run on the incumbent and the outputs are
        # compared — the int8-rollout guard, where a quantized canary
        # can be fast AND wrong, which neither the failure nor the
        # latency check would ever catch
        self._shadow_fraction = min(1.0, max(0.0, _env.get_float(
            "MXNET_QUANTIZE_SHADOW", 0.0)))
        self._shadow_tol = _env.get_float(
            "MXNET_QUANTIZE_SHADOW_TOL", 0.1)

    # -- registration / lifecycle --------------------------------------

    @property
    def default_model(self):
        with self._lock:
            return self._default

    def models(self):
        with self._lock:
            return sorted(self._models)

    def _model(self, name):
        with self._lock:
            m = self._models.get(name)
            deployed = sorted(self._models)
        if m is None:
            raise MXNetError(
                f"unknown model {name!r} (deployed: "
                f"{', '.join(deployed) or 'none'})")
        return m

    def deploy(self, name, session, version=None, canary_fraction=None):
        """Register a model version; returns the version number. The
        first version of ``name`` activates immediately (atomic, via
        the ``model_swap`` seam); later versions start as a canary
        taking ``canary_fraction`` of non-critical traffic."""
        with self._lock:
            if self._closed:
                raise MXNetError("repository is closed")
            m = self._models.setdefault(name, _Model(name))
            if self._default is None:
                self._default = name
        try:
            return self._deploy_under_model_lock(
                m, name, session, version, canary_fraction)
        except Exception:
            # a failed FIRST activation (model_swap fault, batcher
            # construction) must not leave a half-registered model
            # behind. Reacquire in the declared repository -> model
            # order — the pre-r22 cleanup took the repository lock
            # while still holding the model lock, the one true
            # lock-order inversion the witness found in the tree.
            with self._lock:
                with m.lock:
                    if not m.versions:
                        self._models.pop(name, None)
                        if self._default == name:
                            self._default = next(
                                iter(sorted(self._models)), None)
            raise

    def _deploy_under_model_lock(self, m, name, session, version,
                                 canary_fraction):
        with m.lock:
            ver = int(version) if version is not None else \
                (max(m.versions) + 1 if m.versions else 1)
            if ver in m.versions:
                raise MXNetError(
                    f"model {name!r} version {ver} already deployed")
            if m.canary is not None:
                raise MXNetError(
                    f"model {name!r} already has canary v{m.canary} in "
                    "flight; promote or roll it back first")
            if getattr(session, "label", None) is None and \
                    hasattr(session, "label"):
                session.label = f"{name}@v{ver}"
            vh = _Version(ver, session,
                          DynamicBatcher(session, **self._batcher_kwargs))
            if m.active is None:
                # first version: activate or die
                try:
                    self._activate_locked(m, ver, {ver: vh})
                except Exception:
                    vh.batcher.close()
                    raise
                m.versions[ver] = vh
                m.state = "serving"
                return ver
            m.versions[ver] = vh
            m.canary = ver
            m.canary_fraction = float(
                canary_fraction if canary_fraction is not None
                else self._canary_fraction)
            m.canary_breaker = CircuitBreaker(
                threshold=self._canary_threshold,
                name=f"canary {name}@v{ver}")
            m.canary_successes = 0
            m.canary_failures = 0
            m.canary_lat_ema = None
            m.incumbent_lat_ema = None
            m._tick = 0
            m._shadow_tick = 0
            m.state = "canary"
            m.last_transition = f"canary v{ver} deployed"
            METRICS.bump("canary_deploys")
            return ver

    # kept as an alias: "add a model" reads better at call sites that
    # never roll versions
    add = deploy

    def _activate_locked(self, m, version, versions=None):
        """Atomic active-pointer swap, the ``model_swap`` fault seam.
        An injected fire aborts BEFORE the pointer moves — the
        incumbent stays active and in-flight requests are untouched."""
        _faults.maybe_fail("model_swap")
        m.active = version
        m.last_transition = f"v{version} activated"
        METRICS.bump("model_swaps")

    def promote(self, name):
        """Promote the canary to active (atomic hot-swap). The old
        version's batcher stays alive — rollback after promote is
        instant re-activation, no recompile. When both versions are
        stateful, the incumbent's live sessions MIGRATE into the
        successor's state store under the model lock (submit also takes
        it), so no request can observe the new active version without
        its state — a promote drops zero mid-stream decodes."""
        m = self._model(name)
        with m.lock:
            if m.canary is None:
                raise MXNetError(f"model {name!r} has no canary to "
                                 "promote")
            incumbent = m.versions.get(m.active)
            self._activate_locked(m, m.canary)
            m.canary = None
            m.canary_breaker = None
            m.state = "serving"
            m.last_transition = f"canary v{m.active} promoted"
            METRICS.bump("canary_promotions")
            self._migrate_sessions_locked(
                m, incumbent, m.versions[m.active])
            logging.info("serving: model %s canary v%d promoted",
                         name, m.active)

    @staticmethod
    def _migrate_sessions_locked(m, src_vh, dst_vh):
        """Hand the outgoing version's live session state to the new
        active one. Failures are logged, never raised — the swap
        already happened, and an un-migrated session surfaces as a
        clean retryable SessionEvicted on its next step, not a torn
        promote."""
        src = getattr(getattr(src_vh, "session", None),
                      "state_store", None)
        dst = getattr(getattr(dst_vh, "session", None),
                      "state_store", None)
        if src is None or dst is None or src is dst:
            return
        try:
            n = dst.restore_state(src.export_state())
            if n:
                logging.info(
                    "serving: model %s promote migrated %d live "
                    "session(s) to v%d", m.name, n, dst_vh.version)
        except Exception:  # noqa: BLE001 — promote must not unwind
            logging.exception(
                "serving: model %s promote could not migrate live "
                "sessions to v%d", m.name, dst_vh.version)

    def rollback(self, name, reason="operator request"):
        """Cancel the canary; all traffic returns to the incumbent.
        Deliberately seam-free and unconditional — the escape hatch
        must always work."""
        m = self._model(name)
        with m.lock:
            if m.canary is None:
                return
            ver, m.canary = m.canary, None
            m.canary_breaker = None
            m.state = "rolled_back"
            m.last_transition = f"canary v{ver} rolled back: {reason}"
            METRICS.bump("canary_rollbacks")
            logging.warning("serving: model %s canary v%d rolled back "
                            "(%s)", name, ver, reason)

    def refresh(self, name):
        """Live weight refresh of the ACTIVE version (the
        ``refresh_params`` hot path — same executables, new values)."""
        m = self._model(name)
        with m.lock:
            vh = m.versions[m.active]
        vh.session.refresh_params()

    def export_bundle(self, name, path, version=None):
        """Export a deployment bundle for one model version: warm the
        version's session (resolving every bucket/occupancy executable
        into the local artifact cache), then pack those artifacts into
        ONE file at ``path``. A replica that imports the bundle
        (``artifact.import_bundle``) before construction serves its
        first response with zero traces and zero XLA compiles. Returns
        the export report (``{"path", "entries", "missing", "bytes"}``)
        with the manifest's model/version attached."""
        from .. import artifact as _artifact

        m = self._model(name)
        with m.lock:
            ver = int(version) if version is not None else m.active
            vh = m.versions.get(ver)
            if vh is None:
                raise MXNetError(
                    f"model {name!r} has no version {ver} (deployed: "
                    f"{sorted(m.versions)})")
        sess = vh.session
        sess.warmup()
        fps = sess.artifact_fingerprints()
        if not fps:
            raise MXNetError(
                f"model {name!r} v{ver} has no disk-cacheable artifacts "
                "(no graph signature, or the compile cache is disabled)")
        # fused pad/slice executables resolved by served traffic ride
        # along (process-scoped: bundles are per-replica deployment
        # sets, and a helper another model resolved still warms this
        # replica's cache harmlessly)
        from ..kernels import serving_fused as _sf

        fps = list(fps) + _sf.fusion_artifact_fingerprints()
        report = _artifact.export_bundle(
            path, fps,
            manifest={"model": name, "version": ver,
                      "buckets": list(getattr(sess, "buckets", []))})
        report["model"] = name
        report["version"] = ver
        return report

    def close(self):
        """Drain every batcher of every version (engine.close()
        order), then release session resources (a stateful session's
        state-store metrics probe). Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            models = list(self._models.values())
        for m in models:
            with m.lock:
                versions = list(m.versions.values())
            for vh in versions:
                vh.batcher.close()
                close = getattr(vh.session, "close", None)
                if close is not None:
                    close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- the request path ----------------------------------------------

    def submit(self, name, *inputs, timeout_ms=None, slo_class=None,
               block=False, session_id=None):
        """Route one request: canary slice (deterministic, non-critical
        only) or incumbent. Returns a Future; canary execution
        failures fall back to the incumbent transparently. A stateful
        request (``session_id``) never rides the canary — its state
        slot lives in the incumbent's store."""
        from .admission import normalize_class

        m = self._model(name)
        cls = normalize_class(slo_class)
        with m.lock:
            if m.active is None:
                raise MXNetError(f"model {name!r} has no active version")
            incumbent = m.versions[m.active]
            canary = m.versions.get(m.canary) \
                if m.canary is not None else None
            use_canary = False
            if canary is not None and cls != SLO_CLASSES[0] and \
                    session_id is None:
                # counter routing: request k rides the canary iff the
                # integer part of k*fraction advanced — exactly
                # fraction of eligible traffic, deterministically
                # (stateful requests are not eligible and do not tick)
                m._tick += 1
                f = m.canary_fraction
                use_canary = int(m._tick * f) != int((m._tick - 1) * f)
        if not use_canary:
            t0 = time.monotonic()
            kw = {} if session_id is None else \
                {"session_id": session_id}
            fut = incumbent.batcher.submit(
                *inputs, timeout_ms=timeout_ms, slo_class=cls,
                block=block, **kw)
            if canary is not None:
                # sample incumbent latency while a canary is under
                # evaluation — the baseline for the regression check
                fut.add_done_callback(
                    lambda f: self._note_incumbent(m, f, t0))
            return fut
        return self._submit_canary(m, canary, incumbent, inputs,
                                   timeout_ms, cls, block)

    def predict(self, name, *inputs, timeout_ms=None, slo_class=None,
                session_id=None):
        """Blocking convenience over :meth:`submit`."""
        fut = self.submit(name, *inputs, timeout_ms=timeout_ms,
                          slo_class=slo_class, session_id=session_id)
        return fut.result(timeout=60.0)

    def _submit_canary(self, m, canary, incumbent, inputs, timeout_ms,
                       cls, block):
        from concurrent.futures import Future

        METRICS.bump("canary_requests")
        outer = Future()
        t0 = time.monotonic()
        shadow = None
        if self._shadow_fraction > 0.0:
            with m.lock:
                # same counter routing as the canary slice: exactly
                # shadow_fraction of canary requests get a duplicate
                # incumbent run to diff against, no RNG flakes
                m._shadow_tick += 1
                sf = self._shadow_fraction
                take = int(m._shadow_tick * sf) != \
                    int((m._shadow_tick - 1) * sf)
            if take:
                try:
                    shadow = incumbent.batcher.submit(
                        *inputs, timeout_ms=timeout_ms, slo_class=cls)
                except Exception:  # noqa: BLE001 — shadow is advisory;
                    # a full incumbent queue must not fail the request
                    shadow = None
        try:
            inner = canary.batcher.submit(
                *inputs, timeout_ms=timeout_ms, slo_class=cls,
                block=block)
        except ValueError:
            raise  # invalid input — the model didn't fail
        except Exception:  # noqa: BLE001 — backpressure/shed on the
            # canary lane must not surface to the client; the
            # incumbent takes the request (no health accounting — a
            # full queue is load, not model badness)
            return incumbent.batcher.submit(
                *inputs, timeout_ms=timeout_ms, slo_class=cls,
                block=block)

        def _done(f):
            err = f.exception()
            if err is None:
                if shadow is not None:
                    shadow.add_done_callback(
                        lambda g: self._shadow_check(
                            m, canary.version, f, g))
                self._canary_success(m, canary.version,
                                     time.monotonic() - t0)
                if outer.set_running_or_notify_cancel():
                    outer.set_result(f.result())
                return
            self._canary_failure(m, canary.version, err)
            # transparent fallback: the client sees the incumbent's
            # answer, the canary's failure lives only in metrics
            METRICS.bump("canary_fallbacks")
            try:
                fb = incumbent.batcher.submit(
                    *inputs, timeout_ms=timeout_ms, slo_class=cls)
            except Exception as e2:  # noqa: BLE001 — delivered on future
                if outer.set_running_or_notify_cancel():
                    outer.set_exception(e2)
                return
            fb.add_done_callback(lambda g: self._chain(g, outer))

        inner.add_done_callback(_done)
        return outer

    @staticmethod
    def _chain(src, dst):
        if not dst.set_running_or_notify_cancel():
            return
        err = src.exception()
        if err is None:
            dst.set_result(src.result())
        else:
            dst.set_exception(err)

    # -- canary health accounting --------------------------------------

    def _note_incumbent(self, m, fut, t0):
        if fut.exception() is not None:
            return
        dt = time.monotonic() - t0
        with m.lock:
            prev = m.incumbent_lat_ema
            m.incumbent_lat_ema = dt if prev is None else \
                (1 - _LAT_ALPHA) * prev + _LAT_ALPHA * dt

    def _canary_success(self, m, version, dt):
        promote = False
        with m.lock:
            if m.canary != version:
                return  # already promoted/rolled back
            m.canary_successes += 1
            prev = m.canary_lat_ema
            m.canary_lat_ema = dt if prev is None else \
                (1 - _LAT_ALPHA) * prev + _LAT_ALPHA * dt
            # sustained latency regression counts against the breaker
            # too — a canary that "works" at 10x latency is a failed
            # rollout, and routing the verdict through the breaker
            # keeps ONE rollback mechanism
            if (m.canary_successes >= _MIN_LAT_SAMPLES and
                    m.incumbent_lat_ema is not None and
                    m.canary_lat_ema >
                    self._canary_latency_x * m.incumbent_lat_ema):
                m.canary_breaker.record_failure()
                if m.canary_breaker.state != "closed":
                    self._rollback_locked(
                        m, f"latency regression ({m.canary_lat_ema * 1e3:.1f}"
                           f" ms vs incumbent "
                           f"{m.incumbent_lat_ema * 1e3:.1f} ms)")
                    return
            if (m.canary_successes >= self._canary_min_requests and
                    m.canary_breaker.state == "closed"):
                promote = True
        if promote:
            try:
                self.promote(m.name)
            except Exception as e:  # noqa: BLE001 — keep serving on the
                # incumbent; an aborted swap (model_swap fault) leaves
                # the canary under evaluation and the next clean
                # completion retries the promotion
                logging.warning("serving: model %s auto-promote failed "
                                "(%s: %s); canary stays under "
                                "evaluation", m.name,
                                type(e).__name__, e)

    def _shadow_check(self, m, version, canary_fut, shadow_fut):
        """The MXNET_QUANTIZE_SHADOW accuracy gate: diff one canary
        answer against the incumbent's for the same inputs. A relative
        deviation past MXNET_QUANTIZE_SHADOW_TOL is ``record_failure``
        on the canary breaker — same single rollback mechanism as
        execution failures and latency regressions — so a quantized
        canary that is fast but numerically wrong still rolls back with
        zero client-visible errors (the client already has its
        answer)."""
        if shadow_fut.exception() is not None:
            return  # incumbent trouble is not canary badness
        METRICS.bump("canary_shadow_checks")
        try:
            dev = _rel_deviation(canary_fut.result(),
                                 shadow_fut.result())
        except Exception:  # noqa: BLE001 — advisory path, never raise
            logging.exception("serving: model %s shadow comparison "
                              "failed", m.name)
            return
        if dev <= self._shadow_tol:
            return
        METRICS.bump("canary_shadow_mismatches")
        with m.lock:
            if m.canary != version:
                return
            m.canary_breaker.record_failure()
            if m.canary_breaker.state != "closed":
                self._rollback_locked(
                    m, f"shadow accuracy deviation {dev:.4f} > "
                       f"tolerance {self._shadow_tol:g}")

    def _canary_failure(self, m, version, err):
        with m.lock:
            if m.canary != version:
                return
            m.canary_failures += 1
            METRICS.bump("canary_failures")
            m.canary_breaker.record_failure()
            # the breaker leaving "closed" IS the rollback trigger —
            # with MXNET_RESILIENCE=0 breakers never trip and canaries
            # only roll back by operator hand, documented behavior
            if m.canary_breaker.state != "closed":
                self._rollback_locked(
                    m, f"breaker tripped after {m.canary_failures} "
                       f"failure(s) ({type(err).__name__}: {err})")

    def _rollback_locked(self, m, reason):
        ver, m.canary = m.canary, None
        m.canary_breaker = None
        m.state = "rolled_back"
        m.last_transition = f"canary v{ver} rolled back: {reason}"
        METRICS.bump("canary_rollbacks")
        logging.warning("serving: model %s canary v%d auto-rollback "
                        "(%s)", m.name, ver, reason)

    # -- observability -------------------------------------------------

    def model_states(self):
        """{name: lifecycle snapshot} — the /healthz ``models`` block."""
        with self._lock:
            models = dict(self._models)
        out = {}
        for name, m in sorted(models.items()):
            with m.lock:
                info = {
                    "state": m.state,
                    "active_version": m.active,
                    "versions": sorted(m.versions),
                    "last_transition": m.last_transition,
                }
                if m.canary is not None:
                    info["canary"] = {
                        "version": m.canary,
                        "fraction": m.canary_fraction,
                        "successes": m.canary_successes,
                        "failures": m.canary_failures,
                        "breaker": m.canary_breaker.state,
                    }
                vh = m.versions.get(m.active)
            if vh is not None:
                sess = vh.session
                # one consistent warm/degraded/breaker view under the
                # session's ranked lock (round 23) instead of three
                # independently-raced reads
                if hasattr(sess, "health_snapshot"):
                    snap = sess.health_snapshot()
                else:
                    snap = {"warm": True, "degraded_buckets": [],
                            "open_buckets": []}
                info["warm"] = bool(snap["warm"])
                store = getattr(sess, "state_store", None)
                if store is not None:
                    info["session_state"] = store.stats()
                info["degraded_buckets"] = list(
                    snap["degraded_buckets"])
                info["open_buckets"] = list(snap["open_buckets"])
            out[name] = info
        return out

    def healthz(self):
        """Aggregate health: per-model lifecycle + queue depths per
        SLO class + the live SLO headroom block (minimum across every
        version batcher's admission controller)."""
        models = self.model_states()
        warm = all(i.get("warm", True) for i in models.values())
        degraded = any(i.get("degraded_buckets") or i.get("open_buckets")
                       or i["state"] == "rolled_back"
                       for i in models.values())
        depths = dict.fromkeys(SLO_CLASSES, 0)
        slo = None
        with self._lock:
            all_models = list(self._models.values())
        for m in all_models:
            with m.lock:
                versions = list(m.versions.values())
            for vh in versions:
                for cls, n in vh.batcher.qsize_by_class().items():
                    depths[cls] = depths.get(cls, 0) + n
                adm = getattr(vh.batcher, "admission", None)
                if adm is not None:
                    snap = adm.snapshot()
                    if slo is None or snap["headroom"] < slo["headroom"]:
                        slo = snap
        status = "ok" if warm else "warming"
        if warm and degraded:
            status = "degraded"
        return {
            "status": status,
            "warm": warm,
            "models": models,
            "queue_depth": sum(depths.values()),
            "queue_depths": depths,
            "slo": slo,
        }
