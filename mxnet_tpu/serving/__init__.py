"""mxnet_tpu.serving — dynamic-batching inference serving.

The production inference path of the framework (ROADMAP north star:
"serves heavy traffic from millions of users"; reference analog: the
MXNet model-server ecosystem over ``SymbolBlock.imports`` artifacts),
built directly on the round-9 compile-cache primitives:

- :class:`~mxnet_tpu.serving.session.InferenceSession` — eval-mode,
  no-tape forward compiled ONCE per batch-size bucket (AOT through
  ``utils/compile_cache.py``); a warm process deserializes every bucket
  and serves its first request with zero traces and zero XLA compiles.
- :class:`~mxnet_tpu.serving.batcher.DynamicBatcher` — per-SLO-class
  priority lanes with backpressure, deadline-aware micro-batch
  coalescing under a ``max_latency_ms`` flush deadline, per-request
  validation/timeout isolation, engine.close()-style graceful drain.
  For STATEFUL sessions it runs a continuous-batching step loop:
  sequences join and leave the executing batch between decode steps
  (gather live slots -> one fused step -> scatter), no prefix
  re-execution.
- :class:`~mxnet_tpu.serving.state.SessionStateStore` — slot-indexed,
  device-resident per-client recurrent/KV state pool with session
  affinity, TTL + LRU eviction under a byte budget
  (:class:`~mxnet_tpu.serving.state.SessionEvicted` is the clean
  retryable eviction error), and checkpoint/migration payloads
  (``export_state``/``restore_state``) so restarts and canary
  promotes resume live streams.
- :class:`~mxnet_tpu.serving.admission.AdmissionController` —
  SLO-aware admission control: sheds best-effort load with a fast 503
  + ``Retry-After`` (:class:`~mxnet_tpu.serving.admission.ShedLoad`)
  when queue-depth / rolling-p99 headroom says the high-priority SLO
  is at risk.
- :class:`~mxnet_tpu.serving.repository.ModelRepository` — N models x
  versions behind per-model batchers, atomic hot-swap, canary rollout
  with breaker-driven auto-rollback.
- :class:`~mxnet_tpu.serving.server.ModelServer` — stdlib
  ``ThreadingHTTPServer`` JSON/npy endpoint with ``/healthz``
  (queue depths + SLO headroom + canary states), ``/models`` and
  Prometheus ``/metrics``.
- :mod:`~mxnet_tpu.serving.metrics` — p50/p95/p99 latency histograms
  (global + rolling per-SLO-class), queue depth, batch-size histogram,
  QPS, goodput, shed/canary counters; surfaced via
  ``profiler.serving_counters()`` and the ``SERVING`` runtime feature.

Quick start::

    import mxnet_tpu as mx
    from mxnet_tpu import serving

    sess = serving.InferenceSession.load("export/mymodel",
                                         input_shapes=[(1, 784)])
    with serving.ModelServer(sess, port=8080) as srv:
        ...  # POST /predict, GET /healthz, GET /metrics

Knobs: ``MXNET_SERVING`` (0 degrades the batcher to inline
pass-through), ``MXNET_SERVING_MAX_BATCH`` / ``_MAX_LATENCY_MS`` /
``_QUEUE_DEPTH`` / ``_TIMEOUT_MS`` / ``_WORKERS`` / ``_BUCKETS`` /
``_HOST`` / ``_PORT``, plus the round-13 SLO/canary family
(``_ADMISSION`` / ``_SLO_MS`` / ``_SHED_HEADROOM`` /
``_RETRY_AFTER_MS`` / ``_CANARY_FRACTION`` / ``_CANARY_MIN_REQUESTS``
/ ``_CANARY_THRESHOLD`` / ``_CANARY_LATENCY_X``) and the round-16
session-state family (``_STATE_SLOTS`` / ``_STATE_BUDGET_MB`` /
``_STATE_TTL_S``) — see docs/SERVING.md and docs/ENV_VARS.md.
"""
from __future__ import annotations

__all__ = ["InferenceSession", "DynamicBatcher", "ModelServer",
           "ModelRepository", "AdmissionController", "ShedLoad",
           "ServerBusy", "RequestTimeout", "SLO_CLASSES",
           "SessionStateStore", "SessionEvicted",
           "FleetRouter", "Replica", "ReplicaProcess", "spawn_replica",
           "fleet_counters", "reset_fleet_counters",
           "parse_buckets", "serving_enabled", "serving_stats",
           "reset_serving_counters", "prometheus_text", "METRICS"]


def serving_enabled():
    """MXNET_SERVING knob (default on): 0 disables dynamic batching —
    batchers execute requests inline, pass-through — and reports the
    ``SERVING`` runtime feature as off. Read per use so tests can
    toggle without reimport."""
    from .. import env as _env

    return _env.get_bool("MXNET_SERVING", True)


from .metrics import (METRICS, SLO_CLASSES, prometheus_text,  # noqa: E402
                      reset_serving_counters, serving_stats)
from .state import SessionEvicted, SessionStateStore  # noqa: E402
from .session import InferenceSession, parse_buckets  # noqa: E402
from .batcher import DynamicBatcher, RequestTimeout, ServerBusy  # noqa: E402
from .admission import AdmissionController, ShedLoad  # noqa: E402
from .repository import ModelRepository  # noqa: E402
from .server import ModelServer  # noqa: E402
from .fleet import (FleetRouter, Replica, ReplicaProcess,  # noqa: E402
                    fleet_counters, reset_fleet_counters,
                    spawn_replica)
