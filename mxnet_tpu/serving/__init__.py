"""mxnet_tpu.serving — dynamic-batching inference serving.

The production inference path of the framework (ROADMAP north star:
"serves heavy traffic from millions of users"; reference analog: the
MXNet model-server ecosystem over ``SymbolBlock.imports`` artifacts),
built directly on the round-9 compile-cache primitives:

- :class:`~mxnet_tpu.serving.session.InferenceSession` — eval-mode,
  no-tape forward compiled ONCE per batch-size bucket (AOT through
  ``utils/compile_cache.py``); a warm process deserializes every bucket
  and serves its first request with zero traces and zero XLA compiles.
- :class:`~mxnet_tpu.serving.batcher.DynamicBatcher` — bounded request
  queue with backpressure, micro-batch coalescing under a
  ``max_latency_ms`` flush deadline, per-request validation/timeout
  isolation, engine.close()-style graceful drain.
- :class:`~mxnet_tpu.serving.server.ModelServer` — stdlib
  ``ThreadingHTTPServer`` JSON/npy endpoint with ``/healthz`` and
  Prometheus ``/metrics``.
- :mod:`~mxnet_tpu.serving.metrics` — p50/p95/p99 latency histograms,
  queue depth, batch-size histogram, QPS, warm-start counters; surfaced
  via ``profiler.serving_counters()`` and the ``SERVING`` runtime
  feature.

Quick start::

    import mxnet_tpu as mx
    from mxnet_tpu import serving

    sess = serving.InferenceSession.load("export/mymodel",
                                         input_shapes=[(1, 784)])
    with serving.ModelServer(sess, port=8080) as srv:
        ...  # POST /predict, GET /healthz, GET /metrics

Knobs: ``MXNET_SERVING`` (0 degrades the batcher to inline
pass-through), ``MXNET_SERVING_MAX_BATCH`` / ``_MAX_LATENCY_MS`` /
``_QUEUE_DEPTH`` / ``_TIMEOUT_MS`` / ``_WORKERS`` / ``_BUCKETS`` /
``_HOST`` / ``_PORT`` — see docs/SERVING.md and docs/ENV_VARS.md.
"""
from __future__ import annotations

__all__ = ["InferenceSession", "DynamicBatcher", "ModelServer",
           "ServerBusy", "RequestTimeout", "parse_buckets",
           "serving_enabled", "serving_stats", "reset_serving_counters",
           "prometheus_text", "METRICS"]


def serving_enabled():
    """MXNET_SERVING knob (default on): 0 disables dynamic batching —
    batchers execute requests inline, pass-through — and reports the
    ``SERVING`` runtime feature as off. Read per use so tests can
    toggle without reimport."""
    from .. import env as _env

    return _env.get_bool("MXNET_SERVING", True)


from .metrics import (METRICS, prometheus_text,  # noqa: E402
                      reset_serving_counters, serving_stats)
from .session import InferenceSession, parse_buckets  # noqa: E402
from .batcher import DynamicBatcher, RequestTimeout, ServerBusy  # noqa: E402
from .server import ModelServer  # noqa: E402
