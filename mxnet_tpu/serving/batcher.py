"""Dynamic micro-batcher: coalesce concurrent requests into bucket-sized
model executions.

The throughput lever of the serving subsystem (reference analog: the
MXNet model-server's dynamic batching; same shape as every production
inference queue): requests land on a bounded queue (backpressure —
``submit`` raises :class:`ServerBusy` when full), a worker coalesces
them until ``max_batch_size`` rows are gathered OR the oldest request
has waited ``max_latency_ms``, runs ONE
:class:`~mxnet_tpu.serving.session.InferenceSession` execution over the
concatenated rows (which pads to the session's shape bucket), then
slices per-request outputs back and resolves each request's future.

Failure isolation: every request is validated at ``submit`` time
against the session's input specs, so one malformed input fails alone —
it never reaches a batch, never poisons its neighbors. A request that
outlives its deadline (``timeout_ms``) is failed with
:class:`RequestTimeout` at batch-formation time without executing.

Round 13 replaces the single FIFO with per-SLO-class priority lanes
(:class:`_ClassQueues`): requests carry a class from
:data:`~mxnet_tpu.serving.metrics.SLO_CLASSES` and a deadline, workers
always pop the highest-priority lane first, coalescing is
deadline-aware (the flush timer never waits past the earliest member
deadline minus the rolling exec-latency estimate), and an
:class:`~mxnet_tpu.serving.admission.AdmissionController` sheds
low-priority load at ``submit()`` when SLO headroom runs out.

Graceful shutdown mirrors ``engine.close()``: ``close()`` stops
accepting queued work, drains everything already accepted, joins the
workers, and is idempotent; after close (or with ``MXNET_SERVING=0``)
``submit`` degrades to inline single-request execution so late callers
stay correct — exactly the engine's post-close inline semantics.

Round 16 — **continuous batching** for stateful sessions: a batcher
over a ``state_shapes=`` InferenceSession replaces the coalesce-flush
cycle with a STEP LOOP. Each submit is one decode step of one session
(``session_id=``, one row); the loop keeps per-session FIFO queues and
between decode steps re-forms the executing batch from the head step
of every live session — sequences JOIN the batch the moment they
arrive and LEAVE the moment their queue empties, instead of the whole
batch blocking on its slowest member. One fused step per iteration:
gather the live sessions' state slots from the
:class:`~.state.SessionStateStore`, execute the occupancy-bucket step
executable, scatter the new states back. Affinity holds by
construction — the loop is single-threaded and admits at most one
queued step per session per batch, so a client's steps never
interleave or reorder. SLO admission, per-class queues and
deadline-at-every-exit all survive: admission sheds at submit (with a
slot-occupancy term when the step would allocate a new state slot),
higher classes win batch membership under contention, and expired
steps fail with ``RequestTimeout`` at formation time — their session
state stays put, so a timed-out step is retryable. ``close()`` runs
every accepted step to its boundary and, when a ``state_checkpoint``
manager is attached, checkpoints the session states instead of
dropping them.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future

from ..base import MXNetError
from ..ndarray import NDArray
from ..utils import locks as _locks
from ..telemetry import tracer as _telem
from .metrics import METRICS, SLO_CLASSES

__all__ = ["DynamicBatcher", "ServerBusy", "RequestTimeout"]


class ServerBusy(MXNetError):
    """The request queue is full (backpressure); retry later (HTTP 503)."""


class RequestTimeout(MXNetError):
    """The request outlived its deadline before execution (HTTP 504)."""


_STOP = object()  # queue sentinel, one per worker at close()


class _Request:
    __slots__ = ("arrs", "rows", "future", "t_submit", "deadline",
                 "slo_class", "session_id", "trace_id")

    def __init__(self, arrs, rows, deadline, slo_class="standard",
                 session_id=None):
        self.arrs = arrs  # list[NDArray], one per session input
        self.rows = rows
        self.future = Future()
        self.t_submit = time.monotonic()
        self.deadline = deadline
        self.slo_class = slo_class
        self.session_id = session_id  # stateful decode: one step of sid
        # the request's trace id crosses the queue with it: submit runs
        # on the HTTP handler thread (inside its trace_context), the
        # batch executes on a worker — stamping every worker-side span
        # with the member ids is what threads one request's lifecycle
        # back together in the exported trace
        self.trace_id = _telem.current_trace_id()

    def expired(self, now=None):
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) > self.deadline


class _ClassQueues:
    """Per-SLO-class priority lanes behind one condition variable.

    Presents the slice of the ``queue.Queue`` API the batcher (and its
    tests) use — ``put(timeout=)`` / ``put_nowait`` / ``get(timeout=)``
    / ``get_nowait`` / ``qsize`` / ``maxsize``, raising ``queue.Full``
    / ``queue.Empty`` — but ``get`` pops the highest-priority non-empty
    lane first, each lane is bounded independently (``maxsize`` is
    per class, so a best-effort flood can never crowd critical
    requests out of the queue), and ``_STOP`` sentinels ride an
    unbounded control lane delivered only once every data lane is
    empty — ``close()`` therefore drains all accepted work before the
    workers exit, regardless of class."""

    __slots__ = ("maxsize", "_order", "_lanes", "_ctrl", "_cond")

    def __init__(self, maxsize, classes=SLO_CLASSES):
        self.maxsize = int(maxsize)
        self._order = {c: i for i, c in enumerate(classes)}
        self._lanes = [deque() for _ in classes]
        self._ctrl = deque()
        # guards: _lanes, _ctrl
        self._cond = _locks.RankedCondition("batcher.queue")

    def _lane_locked(self, item):
        cls = getattr(item, "slo_class", "standard")
        return self._lanes[self._order.get(cls, 1)]

    def put(self, item, timeout=None):
        """Append to the item's class lane; ``timeout=None`` blocks,
        ``timeout=0`` is the non-blocking put."""
        with self._cond:
            if item is _STOP:
                self._ctrl.append(item)
                self._cond.notify_all()
                return
            lane = self._lane_locked(item)
            deadline = None if timeout is None else \
                time.monotonic() + timeout
            while len(lane) >= self.maxsize:
                if deadline is None:
                    self._cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise queue.Full
                    self._cond.wait(remaining)
            lane.append(item)
            self._cond.notify_all()

    def put_nowait(self, item):
        self.put(item, timeout=0)

    def get(self, timeout=None):
        """Pop the highest-priority non-empty lane; sentinels only
        when every data lane is empty."""
        with self._cond:
            deadline = None if timeout is None else \
                time.monotonic() + timeout
            while True:
                for lane in self._lanes:
                    if lane:
                        item = lane.popleft()
                        self._cond.notify_all()
                        return item
                if self._ctrl:
                    return self._ctrl.popleft()
                if deadline is None:
                    self._cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise queue.Empty
                    self._cond.wait(remaining)

    def get_nowait(self):
        return self.get(timeout=0)

    def qsize(self):
        with self._cond:
            return sum(len(lane) for lane in self._lanes)

    def qsize_by_class(self):
        with self._cond:
            return {c: len(self._lanes[i])
                    for c, i in self._order.items()}

    def capacity(self):
        # lane list is built once in __init__ and never reassigned;
        # len() of it needs no lock
        return self.maxsize * len(self._lanes)  # graft-lint: allow(L1102)


class DynamicBatcher:
    """Bounded-queue dynamic micro-batcher over an InferenceSession.

    Parameters (all defaulting to their ``MXNET_SERVING_*`` knobs)
    ----------
    session : InferenceSession (or any object with ``validate`` /
        ``predict`` and a ``max_batch`` property)
    max_batch_size : int — coalescing row bound (capped at the
        session's ``max_batch`` so a batch never chunks)
    max_latency_ms : float — flush deadline measured from the OLDEST
        request in the forming batch
    max_queue : int — per-SLO-class bound on queued requests
        (backpressure; a best-effort flood can't evict critical slots)
    timeout_ms : float — default per-request deadline; <= 0 disables
    num_workers : int — batch-formation threads (one is right for one
        accelerator; more only helps when execution itself overlaps)
    admission : bool | None — SLO-aware admission control (None reads
        MXNET_SERVING_ADMISSION; False gives round-10 pure-FIFO
        backpressure semantics)
    state_checkpoint : CheckpointManager | None — stateful batchers
        only: ``close()`` checkpoints the drained session states
        through it (a manager built with ``session_state=`` the
        session's store) instead of dropping live streams
    """

    def __init__(self, session, max_batch_size=None, max_latency_ms=None,
                 max_queue=None, timeout_ms=None, num_workers=None,
                 admission=None, state_checkpoint=None):
        from .. import env as _env
        from . import serving_enabled

        self.session = session
        self._stateful = bool(getattr(session, "stateful", False))
        self._state_ckpt = state_checkpoint
        if state_checkpoint is not None and not self._stateful:
            raise MXNetError("state_checkpoint= requires a stateful "
                             "session (state_shapes=)")
        self._max_batch = int(max_batch_size or _env.get_int(
            "MXNET_SERVING_MAX_BATCH", 32))
        sess_max = getattr(session, "max_batch", None)
        if sess_max:
            self._max_batch = min(self._max_batch, int(sess_max))
        self._max_latency_s = float(
            max_latency_ms if max_latency_ms is not None else
            _env.get_float("MXNET_SERVING_MAX_LATENCY_MS", 5.0)) / 1e3
        self._timeout_s = float(
            timeout_ms if timeout_ms is not None else
            _env.get_float("MXNET_SERVING_TIMEOUT_MS", 2000.0)) / 1e3
        nworkers = int(num_workers or _env.get_int(
            "MXNET_SERVING_WORKERS", 1))
        depth = int(max_queue or _env.get_int(
            "MXNET_SERVING_QUEUE_DEPTH", 256))
        self._queue = _ClassQueues(depth)
        # guards: _closed
        self._lock = _locks.RankedLock("batcher")
        self._closed = False
        self._pass_through = not serving_enabled()
        self._admission = None
        if not self._pass_through:
            from .admission import AdmissionController

            self._admission = AdmissionController(
                self, enabled=admission)
        self._workers = []
        if not self._pass_through:
            # continuous batching is a single-scheduler discipline:
            # one step-loop thread owns batch membership, which is
            # what makes session affinity hold by construction
            if self._stateful:
                nworkers = 1
            loop = self._step_loop if self._stateful \
                else self._worker_loop
            ready = []
            for i in range(max(nworkers, 1)):
                ev = threading.Event()
                ready.append(ev)
                t = threading.Thread(target=loop,
                                     args=(ev,),
                                     name=f"mxnet-serving-batcher-{i}",
                                     daemon=True)
                t.start()
                self._workers.append(t)
            # a constructed batcher is READY: wait out the workers'
            # one-time thread-PRNG priming so the first request never
            # pays it (bounded — a wedged prime must not hang startup)
            for ev in ready:
                ev.wait(timeout=30)
        self._depth_token = METRICS.register_depth_probe(
            self._queue.qsize)

    # -- client side ---------------------------------------------------

    def submit(self, *inputs, timeout_ms=None, block=False,
               slo_class=None, session_id=None):
        """Validate and enqueue one request; returns a
        ``concurrent.futures.Future`` resolving to the request's output
        rows as HOST numpy arrays (one array, or a tuple for
        multi-output models). The batcher is a host-boundary component
        — requests arrive from the network and responses leave to it —
        so coalescing, padding and per-request slicing all run in
        numpy, and each executed batch pays exactly one device upload
        and one download per output. Validation failures raise
        ``ValueError`` immediately — per-request, never
        batch-poisoning. ``slo_class`` is one of
        :data:`~mxnet_tpu.serving.metrics.SLO_CLASSES` (default
        "standard"); when SLO headroom says the high-priority SLO is
        at risk, sheddable classes raise
        :class:`~mxnet_tpu.serving.admission.ShedLoad` here — before
        occupying a queue slot. A full class lane raises
        :class:`ServerBusy` (or blocks when ``block=True``). After
        ``close()`` / under ``MXNET_SERVING=0`` the request runs
        inline.

        Stateful batchers: every submit is ONE decode step of the
        stream named by ``session_id`` (required, one row per step) —
        the server keeps the state, so the payload is just the step's
        input token/frame. The future resolves to that step's output
        row(s); a reclaimed slot rejects with
        :class:`~.state.SessionEvicted` (retryable 503) on exactly
        this stream."""
        # the lifecycle's first span: validation + SLO admission +
        # the queue put, on the caller's thread (inside the HTTP
        # layer's trace_context when one is active). Rejections —
        # ValueError / ShedLoad / ServerBusy — surface as the span's
        # error attr, so shed load is visible in the trace, not just
        # the counters. emit_span (not span): this runs once per
        # request on the client thread, and the flat form skips the
        # nesting bookkeeping — viewers nest by time containment.
        if not _telem.tracing():
            return self._submit_inner(inputs, timeout_ms, block,
                                      slo_class, session_id, None)
        t0 = time.monotonic()
        attrs = {"slo_class": slo_class or "standard"}
        try:
            return self._submit_inner(inputs, timeout_ms, block,
                                      slo_class, session_id, attrs)
        except Exception as e:
            attrs["error"] = type(e).__name__
            raise
        finally:
            _telem.emit_span("serving.admission", "serving", t0,
                             time.monotonic(), **attrs)

    def _submit_inner(self, inputs, timeout_ms, block, slo_class,
                      session_id, sp):
        import numpy as onp

        from .admission import normalize_class

        cls = normalize_class(slo_class)
        METRICS.bump("requests")
        METRICS.bump_class("requests", cls)
        try:
            if self._stateful:
                if session_id is None:
                    raise ValueError(
                        "stateful serving: submit needs session_id= "
                        "(one decode step of one session)")
            elif session_id is not None:
                raise ValueError(
                    "session_id= requires a stateful session "
                    "(state_shapes=)")
            arrs, rows = self.session.validate(*inputs)
            if self._stateful and rows != 1:
                raise ValueError(
                    f"stateful serving: one decode step is one row "
                    f"(got {rows}); stream steps, not batches")
            arrs = [a.asnumpy() if isinstance(a, NDArray)
                    else onp.asarray(a) for a in arrs]
        except ValueError:
            METRICS.bump("invalid")
            raise
        if rows > self._max_batch:
            METRICS.bump("invalid")
            raise ValueError(
                f"request batch {rows} exceeds max_batch_size "
                f"{self._max_batch}; split the request")
        t = self._timeout_s if timeout_ms is None else \
            float(timeout_ms) / 1e3
        deadline = time.monotonic() + t if t > 0 else None
        req = _Request(arrs, rows, deadline, cls,
                       session_id=None if session_id is None
                       else str(session_id))
        with self._lock:
            inline = self._closed or self._pass_through
        if inline:
            METRICS.bump("inline")
            if sp is not None:
                sp["path"] = "inline"
            if self._stateful:
                self._execute_step_batch([req])
            else:
                self._execute([req])
            return req.future
        if self._admission is not None:
            # a step that must ALLOCATE a state slot competes for pool
            # space; steps of already-live sessions never re-pay the
            # occupancy term (their slot is held)
            allocates = self._stateful and \
                not self.session.state_store.has(req.session_id)
            self._admission.check(cls, allocates_state=allocates)
        if block:
            # bounded waits that re-check _closed: a blocking put on a
            # full queue whose consumers close() just joined would
            # otherwise wait forever
            while True:
                try:
                    self._queue.put(req, timeout=0.05)
                    break
                except queue.Full:
                    with self._lock:
                        closed = self._closed
                    if closed:
                        METRICS.bump("inline")
                        self._execute([req])
                        return req.future
        else:
            try:
                self._queue.put_nowait(req)
            except queue.Full:
                METRICS.bump("rejected")
                raise ServerBusy(
                    f"serving queue full ({self._queue.maxsize} "
                    f"{cls} requests); backpressure — retry later"
                ) from None
        if sp is not None:
            sp["path"] = "queued"
        # close() may have finished (workers joined, queue drained)
        # between the _closed check above and our put landing — nobody
        # would ever consume this request. Drain it ourselves;
        # get_nowait is atomic, so racing drains never double-execute.
        with self._lock:
            orphaned = self._closed
        if orphaned:
            self._drain_queue()
        return req.future

    def predict(self, *inputs, timeout_ms=None, slo_class=None,
                session_id=None):
        """Blocking convenience: ``submit(...).result()`` with a result
        wait bounded by the request deadline (plus execution slack)."""
        fut = self.submit(*inputs, timeout_ms=timeout_ms,
                          slo_class=slo_class, session_id=session_id)
        t = self._timeout_s if timeout_ms is None else \
            float(timeout_ms) / 1e3
        return fut.result(timeout=(t + 60.0) if t > 0 else None)

    def qsize(self):
        return self._queue.qsize()

    def qsize_by_class(self):
        """Live queue depth per SLO class (the /healthz
        ``queue_depths`` block)."""
        return self._queue.qsize_by_class()

    def queue_capacity(self):
        """Total queued-request capacity across class lanes (the
        admission controller's queue-headroom denominator)."""
        return self._queue.capacity()

    @property
    def admission(self):
        """The batcher's AdmissionController (None when pass-through)."""
        return self._admission

    # -- worker side ---------------------------------------------------

    def _worker_loop(self, ready=None):
        # prime this thread's PRNG stream NOW: the first next_key() in
        # a fresh thread constructs the thread-local base key (eager
        # PRNGKey + fold_in, ~100ms of one-time XLA compile on CPU) —
        # pay it at worker start, never under the first request
        try:
            from .. import random as mxrandom

            mxrandom.next_key()
        except Exception:  # graft-lint: allow(L501)
            pass
        finally:
            if ready is not None:
                ready.set()
        holdover = None
        while True:
            req = holdover if holdover is not None else self._queue.get()
            holdover = None
            if req is _STOP:
                break
            now = time.monotonic()
            if req.expired(now):
                self._fail_timeout(req)
                continue
            batch = [req]
            rows = req.rows
            # deadline runs from the oldest request's SUBMIT time (the
            # documented bound): time already spent queued behind a
            # busy worker counts against the coalescing window. Past
            # it, the worker stops WAITING for companions but still
            # drains whatever is already queued (get_nowait) — a
            # backed-up queue coalesces full batches instead of
            # degrading to batch=1.
            # Deadline-aware coalescing: never hold a batch past the
            # earliest member's deadline minus the rolling exec-time
            # estimate — waiting longer converts that member into a
            # guaranteed RequestTimeout for the sake of batch size
            margin = METRICS.exec_estimate_s()
            flush_at = req.t_submit + self._max_latency_s
            if req.deadline is not None:
                flush_at = min(flush_at, req.deadline - margin)
            t_co = time.monotonic() if _telem.tracing() else 0.0
            while rows < self._max_batch:
                remaining = flush_at - time.monotonic()
                try:
                    nxt = self._queue.get_nowait() if remaining <= 0 \
                        else self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    # sentinels are anonymous (close() posts one per
                    # worker), so keep this one for our own top-of-loop
                    # exit — finish the formed batch first. A blocking
                    # repost could deadlock against a full queue.
                    holdover = nxt
                    break
                if nxt.expired():
                    self._fail_timeout(nxt)
                    continue
                if rows + nxt.rows > self._max_batch:
                    holdover = nxt  # opens the next batch
                    break
                batch.append(nxt)
                rows += nxt.rows
                if nxt.deadline is not None:
                    flush_at = min(flush_at, nxt.deadline - margin)
            if t_co:
                _telem.emit_span("serving.coalesce", "serving", t_co,
                                 time.monotonic(),
                                 trace_id=batch[0].trace_id,
                                 requests=len(batch), rows=rows)
            METRICS.observe_flush(time.monotonic() - batch[0].t_submit)
            self._execute(batch)

    def _execute(self, batch):
        """One session execution over the batch's concatenated rows;
        fetch outputs to host once, slice numpy views back per request
        and resolve futures. A session failure here is systemic (inputs
        were validated at submit), so it fails the whole batch."""
        import numpy as onp

        tid = batch[0].trace_id
        if _telem.tracing():
            # each member's queue wait, measured from its own submit
            # to batch formation — the span every latency postmortem
            # starts from. emit_span because t_submit predates the
            # tracer's involvement (it was stamped on the HTTP thread).
            now = time.monotonic()
            for r in batch:
                _telem.emit_span("serving.queue_wait", "serving",
                                 r.t_submit, now, trace_id=r.trace_id,
                                 slo_class=r.slo_class)
        try:
            # host-side batch assembly (the session pads to its shape
            # bucket inside predict)
            with _telem.span("serving.pad", cat="serving", trace_id=tid,
                             requests=len(batch)):
                if len(batch) == 1:
                    arrs = batch[0].arrs
                else:
                    arrs = [onp.concatenate(
                        [r.arrs[i] for r in batch], axis=0)
                        for i in range(len(batch[0].arrs))]
            with _telem.span("serving.execute", cat="serving",
                             trace_id=tid,
                             rows=sum(r.rows for r in batch)):
                outs = self.session.predict(*arrs)
                outs = outs if isinstance(outs, tuple) else (outs,)
                # ONE device->host transfer per output; per-request
                # slices are free numpy views
                host = [o.asnumpy() if isinstance(o, NDArray)
                        else onp.asarray(o) for o in outs]
            if len(batch) > 1:
                # every output must be batch-major over exactly the
                # coalesced rows, or per-request slicing is impossible
                # — handing anyone the full array would leak other
                # requests' data, so the batch fails loudly instead
                total = sum(r.rows for r in batch)
                bad = [i for i, h in enumerate(host)
                       if not (h.ndim and h.shape[0] == total)]
                if bad:
                    raise MXNetError(
                        f"output(s) {bad} are not batch-major over "
                        f"{total} coalesced rows (shapes "
                        f"{[host[i].shape for i in bad]}); batched "
                        "serving needs row-independent outputs — use "
                        "max_batch_size=1 or a direct "
                        "InferenceSession for this model")
        except Exception as e:  # noqa: BLE001 — delivered per-future
            for r in batch:
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(e)
                METRICS.observe_request(
                    time.monotonic() - r.t_submit, failed=True,
                    slo_class=r.slo_class, met_deadline=False)
            return
        with _telem.span("serving.respond", cat="serving", trace_id=tid,
                         requests=len(batch)):
            offset = 0
            now = time.monotonic()
            for r in batch:
                if len(batch) == 1:
                    sliced = tuple(host)
                else:
                    sliced = tuple(h[offset:offset + r.rows]
                                   for h in host)
                offset += r.rows
                if r.future.set_running_or_notify_cancel():
                    r.future.set_result(
                        sliced[0] if len(sliced) == 1 else sliced)
                METRICS.observe_request(
                    now - r.t_submit, slo_class=r.slo_class,
                    met_deadline=r.deadline is None or now <= r.deadline)

    # -- continuous batching (stateful sessions) -----------------------

    def _step_loop(self, ready=None):
        """The continuous-batching scheduler: between decode steps,
        re-form the executing batch from the HEAD step of every live
        session — sequences join and leave at step boundaries, never
        blocking on each other's lengths. Single-threaded on purpose
        (see the constructor); per-session FIFO queues keep each
        stream's steps ordered, and one-head-per-session batch
        membership keeps them from ever sharing a fused step."""
        try:
            from .. import random as mxrandom

            mxrandom.next_key()
        except Exception:  # graft-lint: allow(L501)
            pass
        finally:
            if ready is not None:
                ready.set()
        pending = {}  # session_id -> deque[_Request] (FIFO per stream)
        arrival = deque()  # session_ids, join order (stable membership)
        stop = False

        def admit(item):
            q = pending.get(item.session_id)
            if q is None:
                pending[item.session_id] = q = deque()
                arrival.append(item.session_id)
            q.append(item)

        while True:
            # drain the queue without blocking: joiners enter pending
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is _STOP:
                    stop = True
                else:
                    admit(item)
            if not pending:
                if stop:
                    break
                try:
                    item = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                if item is _STOP:
                    stop = True
                else:
                    admit(item)
                continue
            # form the step batch: the head step of each live session,
            # failing expired heads first (deadline-at-every-exit —
            # the state slot stays put, so a timed-out step retries)
            heads = []
            for sid in list(arrival):
                q = pending[sid]
                now = time.monotonic()
                while q and q[0].expired(now):
                    self._fail_timeout(q.popleft())
                if not q:
                    del pending[sid]
                    arrival.remove(sid)
                else:
                    heads.append(q[0])
            if not heads:
                continue
            if len(heads) > self._max_batch:
                # contention: higher SLO classes win membership; the
                # stable sort keeps join order within a class
                order = {c: i for i, c in enumerate(SLO_CLASSES)}
                heads.sort(key=lambda r: order.get(r.slo_class, 1))
                heads = heads[:self._max_batch]
            # coalescing window: hold for joiners only while the batch
            # is under-occupied and no member's flush deadline passed.
            # When every live session already contributed its head the
            # window is skipped — holding can only serve sessions that
            # don't exist yet, and those join at the next boundary.
            if (not stop and len(heads) < self._max_batch
                    and len(heads) < len(pending)):
                margin = METRICS.exec_estimate_s()
                flush_at = min(
                    r.t_submit + self._max_latency_s if r.deadline is
                    None else min(r.t_submit + self._max_latency_s,
                                  r.deadline - margin)
                    for r in heads)
                remaining = flush_at - time.monotonic()
                if remaining > 0:
                    try:
                        item = self._queue.get(timeout=remaining)
                        if item is _STOP:
                            stop = True
                        else:
                            admit(item)
                    except queue.Empty:
                        pass
                    else:
                        continue  # re-form with the joiner aboard
            METRICS.observe_flush(
                time.monotonic() - min(r.t_submit for r in heads))
            self._execute_step_batch(heads)
            # executed heads leave their stream queues; drained
            # streams leave the batch (join/leave at step boundaries)
            for r in heads:
                q = pending.get(r.session_id)
                if q and q[0] is r:
                    q.popleft()
                if q is not None and not q:
                    del pending[r.session_id]
                    arrival.remove(r.session_id)

    def _execute_step_batch(self, batch):
        """One fused decode step over the batch's sessions: acquire
        each stream's state slot (per-request failures — eviction, a
        full pool — reject that ONE future), gather the live slots
        into a dense block, run the occupancy-bucket step executable,
        scatter the new states back, resolve each step's output row.
        A session/executable failure past acquire is systemic: it
        fails every live member and releases the slots UN-stepped, so
        the states still describe the last completed step."""
        import numpy as onp

        store = self.session.state_store
        live, recs = [], []
        for r in batch:
            try:
                if not store.has(r.session_id):
                    store.open_for_step(r.session_id)
                recs.append(store.acquire(r.session_id))
                live.append(r)
            except Exception as e:  # noqa: BLE001 — per-future
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(e)
                METRICS.observe_request(
                    time.monotonic() - r.t_submit, failed=True,
                    slo_class=r.slo_class, met_deadline=False)
        if not live:
            return
        if _telem.tracing():
            now = time.monotonic()
            for r in live:
                _telem.emit_span("serving.queue_wait", "serving",
                                 r.t_submit, now, trace_id=r.trace_id,
                                 slo_class=r.slo_class,
                                 session=r.session_id)
        t0 = time.perf_counter()
        # slot RECORDS, not indices: a paged store routes gather/
        # scatter through each record's page table
        slots = recs
        try:
            with _telem.span("serving.decode_step", cat="serving",
                             trace_id=live[0].trace_id,
                             sessions=len(live)):
                if len(live) == 1:
                    arrs = live[0].arrs
                else:
                    arrs = [onp.concatenate(
                        [r.arrs[i] for r in live], axis=0)
                        for i in range(len(live[0].arrs))]
                states = store.gather(slots)
                outs, news = self.session._run_step(
                    arrs, states, len(live), adopted=True)
                import jax

                # surface step failures BEFORE the scatter: a poisoned
                # write would corrupt every member's resume point
                jax.block_until_ready(news)
                store.scatter(slots, news)
                host = [onp.asarray(o) for o in outs]
        except Exception as e:  # noqa: BLE001 — delivered per-future
            for rec in recs:
                store.release(rec, stepped=False)
            now = time.monotonic()
            for r in live:
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(e)
                METRICS.observe_request(
                    now - r.t_submit, failed=True,
                    slo_class=r.slo_class, met_deadline=False)
            return
        for rec in recs:
            store.release(rec)
        METRICS.bump("decode_steps")
        METRICS.observe_batch(len(live), time.perf_counter() - t0)
        now = time.monotonic()
        for i, r in enumerate(live):
            sliced = tuple(h[i:i + 1] for h in host)
            if r.future.set_running_or_notify_cancel():
                r.future.set_result(
                    sliced[0] if len(sliced) == 1 else sliced)
            METRICS.observe_request(
                now - r.t_submit, slo_class=r.slo_class,
                met_deadline=r.deadline is None or now <= r.deadline)

    def _fail_timeout(self, req):
        _telem.instant("serving.timeout", cat="serving",
                       trace_id=req.trace_id, slo_class=req.slo_class)
        if req.future.set_running_or_notify_cancel():
            # the REQUEST's own deadline (submit may have overridden
            # the batcher default)
            budget_ms = (req.deadline - req.t_submit) * 1e3
            req.future.set_exception(RequestTimeout(
                f"request expired after {budget_ms:.0f} ms in queue"))
        METRICS.observe_request(time.monotonic() - req.t_submit,
                                failed=True, timed_out=True,
                                slo_class=req.slo_class,
                                met_deadline=False)

    # -- lifecycle -----------------------------------------------------

    def close(self):
        """Graceful shutdown: stop accepting queued work, drain every
        accepted request, join the workers. Idempotent; post-close
        submits run inline (the ``engine.close()`` contract).

        Stateful batchers drain every accepted step to its boundary
        (the step EXECUTES — in-flight streams advance, never drop)
        and then, when a ``state_checkpoint`` manager is attached,
        checkpoint the session states so the streams resume in the
        next process / model version."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for _ in self._workers:
            self._queue.put(_STOP)
        for t in self._workers:
            t.join()
        self._workers = []
        # anything a racing submit slipped in behind the sentinels
        self._drain_queue()
        if self._stateful and self._state_ckpt is not None:
            try:
                store = self.session.state_store
                self._state_ckpt.save(step=store.steps_total)
                self._state_ckpt.wait()
            except Exception:  # graft-lint: allow(L501)
                # close() must complete; a failed state checkpoint is
                # an availability loss, not a shutdown blocker
                import logging

                logging.exception(
                    "serving: session-state checkpoint at close failed")
        METRICS.unregister_depth_probe(self._depth_token)
        if self._admission is not None:
            self._admission.close()

    def _drain_queue(self):
        """Pop and execute everything queued (skipping stray
        sentinels). Called by close() after joining workers, and by a
        submit that discovers its freshly-enqueued request landed in a
        closed (consumer-less) queue. Expired requests fail with
        RequestTimeout here too — the deadline contract ('fails alone,
        without executing') holds on every path a request can leave
        the queue by."""
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is _STOP:
                continue
            if item.expired():
                self._fail_timeout(item)
            elif self._stateful:
                # run the stream to its step boundary (state advances
                # and is checkpointable) instead of dropping the step
                self._execute_step_batch([item])
            else:
                self._execute([item])

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            # the GC runs this at an arbitrary allocation point, under
            # whatever locks the interrupted thread holds — but this
            # instance is unreachable, so no live thread can hold its
            # locks; the inverted-looking order is witness-exempt
            with _locks.exempt("gc finalizer on unreachable batcher"):
                self.close()
        except Exception:  # graft-lint: allow(L501)
            pass
