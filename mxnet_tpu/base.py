"""Base utilities for mxnet_tpu.

TPU-native rebuild of MXNet's base layer. The reference exposes a C ABI with
per-thread error strings (reference: python/mxnet/base.py, src/c_api/); here
errors are plain Python exceptions and the "registry" (reference:
3rdparty/tvm/nnvm op registry consumed via include/mxnet/base.h:35) is a
Python-level op table that autogenerates the `mx.nd.*` namespaces
(reference: python/mxnet/base.py:581 `_init_op_module`).
"""
from __future__ import annotations

import numpy as onp

__all__ = ["MXNetError", "numeric_types", "integer_types", "string_types"]


class MXNetError(RuntimeError):
    """Default error thrown by mxnet_tpu functions.

    Mirrors mxnet.base.MXNetError (reference: python/mxnet/base.py:87).
    """


numeric_types = (float, int, onp.generic)
integer_types = (int, onp.integer)
string_types = (str,)


def check_call(ret):  # pragma: no cover - compat shim, no C ABI here
    """Compat shim for reference code written against the C ABI."""
    if ret:
        raise MXNetError(str(ret))


_registry = {}


def registry(kind):
    """Get (creating if needed) a named registry dict.

    The reference uses dmlc::Registry for ops/iterators/optimizers
    (reference: include/mxnet/base.h:28-36 via dmlc-core); here a dict.
    """
    return _registry.setdefault(kind, {})


def register_entry(kind, name, obj, override=False):
    reg = registry(kind)
    key = name.lower()
    if key in reg and not override:
        raise ValueError(f"{kind} '{name}' already registered")
    reg[key] = obj
    return obj


def lookup_entry(kind, name):
    reg = registry(kind)
    key = name.lower()
    if key not in reg:
        raise ValueError(
            f"{kind} '{name}' not registered. Registered: {sorted(reg)}"
        )
    return reg[key]
