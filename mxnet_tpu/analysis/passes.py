"""Composable verifier passes over a Symbol DAG.

The TPU-native analog of the reference's bound-graph static passes
(reference: src/executor/infer_graph_attr_pass.cc forward/backward
attribute inference with partial info; nnvm pass registry). Each pass is
``pass_fn(ctx)`` over a shared ``PassContext`` (symbol + known
shapes/dtypes + memoized inference results), emitting structured
diagnostics instead of CHECK-aborting:

- ``shape``: partial shape inference (symbol/infer.py) seeded from
  declared ``__shape__`` attrs + caller-known shapes, cross-checked
  against the layer rules (a declared parameter shape that contradicts
  what the consuming layer requires is a GV101 *here*, not an opaque XLA
  error at first forward) and against a whole-graph ``jax.eval_shape``
  of the actual op bodies (GV103 catches the two inference paths
  disagreeing — a bug in the framework itself).
- ``dtype``: forward dtype propagation cross-checked against declared
  ``__dtype__`` attrs (GV102).
- ``structure``: duplicate node names (GV403 — ``tojson`` keys nodes by
  name, so duplicates silently merge on save/load) and dead outputs of
  multi-output nodes (GV401 — computed, never consumed, not a head).

Expensive analyses (shape/dtype inference) are *facts*: named, memoized
on the ``PassContext`` via ``ctx.fact(name)`` and shared between the
verifier and the graph_opt rewrite pipeline, so verify-then-optimize on
bind runs each inference exactly once. Providers register through
``register_fact``; ``analysis/graph_opt.py`` adds purity, use-count and
reachability facts on top of the shape/dtype ones here.
"""
from __future__ import annotations

import ast

import numpy as onp

from ..base import MXNetError
from .diagnostics import DiagnosticReport

__all__ = ["FactError", "PassContext", "PASSES", "register_fact",
           "run_passes", "verify_symbol"]


class FactError:
    """Sentinel fact value: the analysis itself failed. Cached like any
    other fact so a failing inference is not re-attempted per pass."""

    def __init__(self, message):
        self.message = message

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"FactError({self.message!r})"


#: fact name -> provider(ctx); see ``register_fact``
FACT_PROVIDERS = {}


def register_fact(name, provider):
    """Install a fact provider. Facts are computed at most once per
    ``PassContext`` (memoized by ``ctx.fact``)."""
    FACT_PROVIDERS[name] = provider
    return provider


def _opt_count(name, n=1):
    # analysis-run counters live with the optimizer's counter table;
    # lazy import (graph_opt imports this module at load)
    try:
        from .graph_opt import _count
    except Exception:  # pragma: no cover - partial-import window
        return
    _count(name, n)


class PassContext:
    def __init__(self, symbol, shapes=None, dtypes=None, subject=None):
        self.symbol = symbol
        self.known_shapes = {k: tuple(v) for k, v in (shapes or {}).items()}
        self.known_dtypes = {k: onp.dtype(v)
                             for k, v in (dtypes or {}).items()}
        self.report = DiagnosticReport(subject=subject)
        self.var_shapes = None  # filled by the shape pass
        self.out_shapes = None
        self.facts = {}  # fact name -> cached analysis result
        self.passes_run = set()  # verifier pass names already run

    def fact(self, name):
        """Memoized analysis result; computed by the registered
        provider on first request, shared by every later consumer
        (verifier passes and rewrite passes alike)."""
        if name in self.facts:
            _opt_count("fact_cache_hits")
            return self.facts[name]
        value = FACT_PROVIDERS[name](self)
        self.facts[name] = value
        return value

    # -- graph helpers ------------------------------------------------------
    def nodes(self):
        """Walked nodes, de-duplicated: output views made by __getitem__
        share the base node's _inputs/_kwargs identities — collapse them
        to one representative so per-node passes fire once per real op."""
        seen, out = set(), []
        for s in self.symbol._walk():
            if s._group is not None:
                continue
            key = self.node_key(s)
            if key in seen:
                continue
            seen.add(key)
            out.append(s)
        return out

    @staticmethod
    def node_key(s):
        if s._op is None:
            return ("var", s._name)
        return (s._op, id(s._inputs), id(s._kwargs))

    def heads(self):
        return (self.symbol._group if self.symbol._group
                else [self.symbol])

    def declared_shapes(self):
        """Variable shapes declared via ``__shape__`` attrs."""
        out = {}
        for s in self.nodes():
            if s._op is None and "__shape__" in s._attrs:
                try:
                    out[s._name] = tuple(
                        ast.literal_eval(s._attrs["__shape__"]))
                except (ValueError, SyntaxError):
                    pass
        return out

    def declared_dtypes(self):
        out = {}
        for s in self.nodes():
            if s._op is None and "__dtype__" in s._attrs:
                try:
                    out[s._name] = onp.dtype(s._attrs["__dtype__"])
                except TypeError:
                    pass
        return out


# ---------------------------------------------------------------------------
# shape pass

def _merge_known(ctx):
    """Caller-known shapes win over declared attrs; a conflict between
    the two is itself a GV101."""
    declared = ctx.declared_shapes()
    merged = dict(declared)
    for name, shp in ctx.known_shapes.items():
        if name in declared and tuple(declared[name]) != tuple(shp):
            ctx.report.emit(
                "GV101",
                f"variable '{name}' is declared with shape "
                f"{declared[name]} but bound with shape {tuple(shp)}",
                node=name,
                hint="fix the Variable(shape=...) declaration or the "
                     "bound array")
        merged[name] = tuple(shp)
    return merged


def _shapes_fact(ctx):
    """Partial shape inference as a cached fact: ``(var_shapes,
    out_shapes)`` or a ``FactError``. Merging is silent here — conflict
    diagnostics belong to ``shape_pass`` (via ``_merge_known``), which
    may not have run when a rewrite pass asks for shapes."""
    from ..symbol.infer import infer_shapes

    known = dict(ctx.declared_shapes())
    known.update(ctx.known_shapes)
    _opt_count("shape_analysis_runs")
    try:
        return infer_shapes(ctx.symbol, known, allow_unknown=True)
    except MXNetError as e:
        return FactError(str(e))


def _dtypes_fact(ctx):
    """Forward dtype propagation as a cached fact: ``(var_types,
    out_types)`` or a ``FactError``."""
    from ..symbol.infer import infer_types

    known = dict(ctx.declared_dtypes())
    known.update(ctx.known_dtypes)
    _opt_count("dtype_analysis_runs")
    try:
        return infer_types(ctx.symbol, known)
    except Exception as e:
        return FactError(str(e))


register_fact("shapes", _shapes_fact)
register_fact("dtypes", _dtypes_fact)


def shape_pass(ctx):
    from ..symbol.infer import _array_arg_names, _param_shape_rules
    from ..ndarray import registry as _registry

    _merge_known(ctx)  # emits GV101 on declared-vs-bound conflicts
    result = ctx.fact("shapes")
    if isinstance(result, FactError):
        ctx.report.emit(
            "GV101", result.message,
            hint="check the input shapes fed to this graph")
        return
    var_shapes, out_shapes = result
    ctx.var_shapes, ctx.out_shapes = var_shapes, out_shapes

    # cross-check KNOWN parameter shapes against the layer rules the
    # partial-inference pass would use to derive them: the reference's
    # bidirectional FInferShape consistency, forward half
    for node in ctx.nodes():
        if node._op is None:
            continue
        opdef = _registry.get_op(node._op)
        if opdef is None:
            ctx.report.emit(
                "GV101", f"op '{node._op}' is not registered",
                node=node._name)
            continue
        arg_names = _array_arg_names(opdef)
        in_shapes = {}
        for i, inp in enumerate(node._inputs):
            s = var_shapes.get(inp._name) if inp._op is None else None
            if s is not None:
                in_shapes[i] = tuple(s)
        if 0 not in in_shapes:
            # data shape unknown at this node under partial info — the
            # rules need it; nothing to cross-check
            continue
        try:
            rules = _param_shape_rules(node._op, node._kwargs, in_shapes,
                                       arg_names)
        except Exception:
            continue  # a rule that cannot run is not a user error
        for i, want in rules.items():
            if i >= len(node._inputs):
                continue
            inp = node._inputs[i]
            if inp._op is not None:
                continue
            have = var_shapes.get(inp._name)
            if have is not None and tuple(have) != tuple(want):
                ctx.report.emit(
                    "GV101",
                    f"parameter '{inp._name}' has shape {tuple(have)} "
                    f"but op '{node._op}' ({node._name}) requires "
                    f"{tuple(want)} given data shape {in_shapes[0]}",
                    node=f"{node._name}/{inp._name}",
                    hint=f"declare '{inp._name}' with shape "
                         f"{tuple(want)} or fix the layer config")


def eval_shape_cross_check(ctx):
    """Whole-graph ``jax.eval_shape`` over the real op bodies vs the
    inference pass — a desync means symbol/infer.py and the executable
    semantics have drifted (GV103). Runs only when every argument shape
    resolved (full information)."""
    import jax

    from ..ndarray import NDArray

    if ctx.var_shapes is None or ctx.out_shapes is None:
        return
    if any(s is None for s in ctx.out_shapes):
        return
    symbol = ctx.symbol
    names = symbol.list_arguments() + symbol.list_auxiliary_states()
    shapes = [ctx.var_shapes.get(n) for n in names]
    if any(s is None for s in shapes):
        return  # partial info: nothing sound to compare
    specs = [jax.ShapeDtypeStruct(tuple(s), onp.float32) for s in shapes]

    def g(*vals):
        from .. import autograd

        with autograd.pause():
            feed = {n: NDArray(v) for n, v in zip(names, vals)}
            out = symbol._eval_nodes(feed, {})
        outs = out if isinstance(out, (list, tuple)) else [out]
        return tuple(o.data for o in outs)

    try:
        observed = jax.eval_shape(g, *specs)
    except Exception:
        return  # bodies needing non-float inputs etc.: not comparable
    inferred = [tuple(s) for s in ctx.out_shapes]
    if len(observed) != len(inferred):
        return  # head-view flattening differs; pairwise compare unsound
    for i, (obs, inf) in enumerate(zip(observed, inferred)):
        if tuple(obs.shape) != inf:
            ctx.report.emit(
                "GV103",
                f"output {i}: inference pass says {inf} but the op "
                f"bodies produce {tuple(obs.shape)}",
                node=ctx.heads()[min(i, len(ctx.heads()) - 1)]._name,
                hint="symbol/infer.py has drifted from the op "
                     "registry — file a framework bug")


# ---------------------------------------------------------------------------
# dtype pass

def dtype_pass(ctx):
    declared = ctx.declared_dtypes()
    for name, dt in ctx.known_dtypes.items():
        if name in declared and declared[name] != onp.dtype(dt):
            ctx.report.emit(
                "GV102",
                f"variable '{name}' is declared {declared[name]} but "
                f"bound as {onp.dtype(dt)}",
                node=name,
                hint="fix the Variable(dtype=...) declaration or cast "
                     "the bound array")
    result = ctx.fact("dtypes")
    if isinstance(result, FactError):
        ctx.report.emit("GV102",
                        f"dtype inference failed: {result.message}")
        return
    var_types, _ = result
    for name, want in declared.items():
        have = var_types.get(name)
        if have is not None and onp.dtype(have) != onp.dtype(want):
            ctx.report.emit(
                "GV102",
                f"variable '{name}' is declared {want} but inference "
                f"assigns {have}",
                node=name,
                hint="insert an explicit cast or fix the declaration")


# ---------------------------------------------------------------------------
# structure pass: duplicate names + dead outputs

def structure_pass(ctx):
    # duplicate names: tojson() keys nodes by name — two distinct nodes
    # sharing one silently collapse on save/load round-trip
    by_name = {}
    for node in ctx.nodes():
        if node._name is None:
            continue
        prev = by_name.get(node._name)
        if prev is not None and ctx.node_key(prev) != ctx.node_key(node):
            ctx.report.emit(
                "GV403",
                f"two distinct nodes share the name '{node._name}' "
                f"(ops: {prev._op or 'variable'} and "
                f"{node._op or 'variable'})",
                node=node._name,
                hint="name symbols uniquely; serialization merges "
                     "same-named nodes")
        else:
            by_name[node._name] = node

    # dead outputs: output k of a multi-output node that no consumer
    # reads and that is not exposed as a head
    consumed = {}  # node_key -> set(output indices read)
    for s in ctx.symbol._walk():
        if s._group is not None:
            continue
        for inp in s._inputs:
            consumed.setdefault(ctx.node_key(inp), set()).add(
                inp._output_index)
    live_heads = {}
    for h in ctx.heads():
        key = ctx.node_key(h)
        n_out = getattr(h, "_num_outputs", 1) or 1
        if n_out > 1 and h._output_index == 0 and h._op is not None:
            # a bare multi-output head exposes ALL its outputs
            # (list_outputs); a view head exposes only its index
            live_heads.setdefault(key, set()).update(range(n_out))
        else:
            live_heads.setdefault(key, set()).add(h._output_index)
    for node in ctx.nodes():
        if node._op is None:
            continue
        n_out = getattr(node, "_num_outputs", 1) or 1
        if n_out <= 1:
            continue
        key = ctx.node_key(node)
        live = consumed.get(key, set()) | live_heads.get(key, set())
        dead = sorted(set(range(n_out)) - live)
        if dead:
            ctx.report.emit(
                "GV401",
                f"op '{node._op}' ({node._name}) computes {n_out} "
                f"outputs but outputs {dead} are never consumed",
                node=node._name,
                hint="drop the unused outputs (e.g. fewer split "
                     "sections) or consume them")


PASSES = {
    "shape": shape_pass,
    "eval_shape": eval_shape_cross_check,
    "dtype": dtype_pass,
    "structure": structure_pass,
}

#: default pipeline order — shape first (eval_shape consumes its result)
DEFAULT_PIPELINE = ("shape", "eval_shape", "dtype", "structure")


def run_passes(ctx, passes=None):
    for name in (passes or DEFAULT_PIPELINE):
        PASSES[name](ctx)
        ctx.passes_run.add(name)
    return ctx.report


def verify_symbol(symbol, shapes=None, dtypes=None, passes=None,
                  subject=None):
    """Run the verifier pipeline over a Symbol DAG; returns the
    ``DiagnosticReport`` (not yet dispositioned — call ``.disposition()``
    to apply the MXNET_GRAPH_VERIFY mode)."""
    ctx = PassContext(symbol, shapes=shapes, dtypes=dtypes,
                      subject=subject or getattr(symbol, "_name", None))
    return run_passes(ctx, passes)
