"""Runtime donation/aliasing safety checks.

Buffer donation (``donate_argnums``) *deletes* the donated jax.Array on
backends that honor it — any other holder of that buffer (an autograd
tape node's saved primals, a ``detach()`` snapshot, a user copy) is left
pointing at freed device memory. XLA only reports this lazily, as an
opaque "buffer has been deleted" error at the *next* use; these checks
prove the hazard at donation time and name the holder.

Called from the compiled-dispatch cache (ndarray/registry.py, ``out=``
donation under ``MXNET_EAGER_JIT_DONATE``) and the fused train-step
(gluon/trainer.py, parameter donation under ``MXNET_FUSED_STEP_DONATE``)
when ``MXNET_GRAPH_VERIFY`` is active.
"""
from __future__ import annotations

from .diagnostics import DiagnosticReport, verify_mode

__all__ = ["check_dispatch_donation", "check_param_donation"]


def _tape_aliases(buffers):
    """Map buffer id -> describing string for tape-held aliases."""
    from .. import autograd

    held = {}
    for pos, node in enumerate(getattr(autograd._STATE, "tape", ()) or ()):
        for pr in node.primals:
            held.setdefault(id(pr), f"tape node {pos} "
                                    f"({getattr(node, 'fun', None) and getattr(node.fun, '__name__', 'op') or 'op'})")
    return {b: held[b] for b in buffers if b in held}


def check_dispatch_donation(opname, arr_args, donate_slot, out):
    """Verify an ``out=``-aliasing dispatch may donate its input slot.

    GV202: the to-be-donated buffer also feeds another argument slot of
    the same dispatch (XLA would alias one buffer into two parameters).
    GV201: an autograd tape node still holds the buffer as a saved
    primal — backward would read deleted memory.

    Returns the dispositioned report (raises under =error).
    """
    mode = verify_mode()
    if mode == "off" or donate_slot is None:
        return None
    report = DiagnosticReport(subject=opname)
    donated = arr_args[donate_slot]._data
    for i, a in enumerate(arr_args):
        if i != donate_slot and a._data is donated:
            report.emit(
                "GV202",
                f"op '{opname}': the out= buffer is also argument slot "
                f"{i} — donating would invalidate a live input",
                node=opname,
                hint="pass a distinct array for out=")
    alias = _tape_aliases([id(donated)])
    if alias:
        report.emit(
            "GV201",
            f"op '{opname}': the out= buffer to be donated is still "
            f"held by {alias[id(donated)]} — backward would read "
            "deleted memory",
            node=opname,
            hint="run the in-place update outside autograd.record, or "
                 "disable MXNET_EAGER_JIT_DONATE")
    return report.disposition(mode)


def check_param_donation(param_arrays, subject="fused_step"):
    """Verify fused-step parameter donation: no donated parameter buffer
    may still be referenced by a live tape node (GV201) and no two
    parameters may share one buffer (GV202)."""
    mode = verify_mode()
    if mode == "off":
        return None
    report = DiagnosticReport(subject=subject)
    seen = {}
    bufs = []
    for name, data in param_arrays:
        bufs.append(id(data))
        prev = seen.get(id(data))
        if prev is not None:
            report.emit(
                "GV202",
                f"parameters '{prev}' and '{name}' share one buffer — "
                "donation would free it twice",
                node=name,
                hint="give each parameter its own storage")
        else:
            seen[id(data)] = name
    aliases = _tape_aliases(bufs)
    for name, data in param_arrays:
        holder = aliases.get(id(data))
        if holder is not None:
            report.emit(
                "GV201",
                f"parameter '{name}' is donated to the fused step but "
                f"still held by {holder}",
                node=name,
                hint="call backward() before step(), or keep "
                     "MXNET_FUSED_STEP_DONATE=0")
    return report.disposition(mode)
