"""Dynamic-trace front end: record one eager forward as an event list.

The Symbol passes (passes.py) see the declared graph; hybridized blocks
and raw imperative code have no Symbol to walk. This front end records
ONE paused eager execution — every op dispatch with its input/output
buffer identities, PRNG keys drawn, and ``out=`` donation aliasing —
into a ``GraphTrace``, then runs dataflow passes over the events:

- ``key_reuse``: the same PRNG key consumed by two stochastic dispatches
  (GV301) — the classic jit-unsafety where a key baked into a replayed
  region silently reuses one mask forever;
- ``donation``: an input buffer read after an ``out=``-aliasing dispatch
  rebound it (use-after-donate, GV201: under ``MXNET_EAGER_JIT_DONATE``
  /TPU the old buffer is *deleted*, so that read would fault or return
  garbage), and one buffer appearing in two donated slots of a single
  dispatch (double donation, GV202);
- ``dead_values``: op results that nothing ever consumed and that are
  not among the traced call's outputs (GV401).

Recording works by wrapping ``ndarray.registry.invoke`` (every eager op,
hybridized replay, and symbolic evaluation funnels through it) and
``mxnet_tpu.random.next_key`` (every key draw — through the global
stream, a provider, or a replayer — resolves the module attribute at
call time). Both hooks are removed on exit; the compiled-dispatch cache
keeps working underneath, and since its hit path pre-splits keys through
``next_key`` too, the observed keys are exactly the keys execution uses.
"""
from __future__ import annotations

import contextlib

import numpy as onp

import jax

from .diagnostics import DiagnosticReport

__all__ = ["OpEvent", "GraphTrace", "record_trace", "verify_trace"]


class OpEvent:
    """One dispatched op: names + buffer identities + keys + donation."""

    __slots__ = ("index", "op", "inputs", "outputs", "keys", "donated",
                 "stochastic")

    def __init__(self, index, op, inputs=(), outputs=(), keys=(),
                 donated=(), stochastic=False):
        self.index = index
        self.op = op
        self.inputs = tuple(inputs)    # buffer ids read
        self.outputs = tuple(outputs)  # buffer ids produced
        self.keys = tuple(keys)        # hashable key fingerprints
        self.donated = tuple(donated)  # buffer ids donated/invalidated
        self.stochastic = stochastic or bool(keys)

    def __repr__(self):
        extra = ""
        if self.keys:
            extra += f" keys={len(self.keys)}"
        if self.donated:
            extra += f" donated={len(self.donated)}"
        return f"<OpEvent {self.index}:{self.op}{extra}>"


class GraphTrace:
    def __init__(self, subject=None):
        self.subject = subject
        self.events = []
        self.live_out = set()  # buffer ids returned from the traced call
        # events identify buffers by id(); keep every recorded array
        # alive for the trace's lifetime so a freed buffer's heap
        # address cannot be recycled into a later array and alias two
        # distinct buffers in the dataflow passes
        self._keepalive = []

    def add(self, op, inputs=(), outputs=(), keys=(), donated=(),
            stochastic=False):
        ev = OpEvent(len(self.events), op, inputs, outputs, keys, donated,
                     stochastic)
        self.events.append(ev)
        return ev

    def mark_outputs(self, arrays):
        """Declare the traced call's results (their buffers are live)."""
        for a in arrays:
            d = getattr(a, "_data", a)
            self._keepalive.append(d)
            self.live_out.add(id(d))

    def __len__(self):
        return len(self.events)


def _key_fingerprint(key):
    """Content identity of a PRNG key: two splits never collide, so equal
    content == the same key reused. Tracer keys (inside an enclosing jit
    trace) have no content — fall back to object identity, which still
    catches literal reuse of one tracer."""
    try:
        return tuple(onp.asarray(key).ravel().tolist())
    except Exception:
        return ("tracer", id(key))


@contextlib.contextmanager
def record_trace(subject=None):
    """Record every op dispatch + PRNG key draw into a GraphTrace."""
    from .. import random as _mxrandom
    from ..ndarray import NDArray
    from ..ndarray import registry as _registry

    trace = GraphTrace(subject=subject)
    drawn = []  # keys drawn since the current dispatch began

    # -- key observer: wrap random.next_key itself, so draws through ANY
    # source are seen — the global eager stream, and providers/replayers
    # installed before OR inside the recorded region ---------------------
    orig_next_key = _mxrandom.next_key

    def observed_next_key():
        k = orig_next_key()
        drawn.append(_key_fingerprint(k))
        return k

    # -- invoke wrapper -------------------------------------------------
    orig_invoke = _registry.invoke
    depth = [0]

    def recording_invoke(opdef, args, kwargs):
        if depth[0]:  # nested dispatch (op body calling ops): outer owns
            return orig_invoke(opdef, args, kwargs)
        depth[0] += 1
        start = len(drawn)
        in_datas = [a._data for a in args if isinstance(a, NDArray)]
        # NB: `out` is a destination, not an input — including it here
        # would make every out= dispatch look self-aliasing (donated)
        in_datas += [v._data for k, v in kwargs.items()
                     if k != "out" and isinstance(v, NDArray)]
        trace._keepalive.extend(in_datas)
        in_ids = [id(d) for d in in_datas]
        out_arr = kwargs.get("out")
        donated = []
        if isinstance(out_arr, NDArray):
            out_buf = id(out_arr._data)
            trace._keepalive.append(out_arr._data)
            if out_buf in in_ids:
                # out= aliases a REAL input: under buffer donation the
                # old payload is invalidated by this dispatch
                donated = [out_buf]
        try:
            result = orig_invoke(opdef, args, kwargs)
        finally:
            depth[0] -= 1
        outs = result if isinstance(result, (list, tuple)) else [result]
        out_datas = [o._data for o in outs if isinstance(o, NDArray)]
        trace._keepalive.extend(out_datas)
        out_ids = [id(d) for d in out_datas]
        trace.add(opdef.name, in_ids, out_ids, drawn[start:], donated,
                  stochastic=not opdef.differentiable and
                  len(drawn) > start)
        return result

    _mxrandom.next_key = observed_next_key
    _registry.invoke = recording_invoke
    try:
        yield trace
    finally:
        _registry.invoke = orig_invoke
        _mxrandom.next_key = orig_next_key


# ---------------------------------------------------------------------------
# trace passes

def key_reuse_pass(trace, report):
    seen = {}  # fingerprint -> first event
    for ev in trace.events:
        for fp in ev.keys:
            first = seen.get(fp)
            if first is not None:
                report.emit(
                    "GV301",
                    f"PRNG key consumed by op '{first.op}' (event "
                    f"{first.index}) is consumed again by op '{ev.op}' "
                    f"(event {ev.index}) — both draw the same random "
                    "stream",
                    node=ev.op,
                    hint="split the key (mx.random.next_key / "
                         "key_provider) instead of reusing it")
            else:
                seen[fp] = ev


def donation_pass(trace, report):
    dead = {}  # buffer id -> event that donated it
    for ev in trace.events:
        for buf in ev.inputs:
            donor = dead.get(buf)
            if donor is not None:
                report.emit(
                    "GV201",
                    f"op '{ev.op}' (event {ev.index}) reads a buffer "
                    f"donated by op '{donor.op}' (event {donor.index}) "
                    "— with buffer donation enabled that payload is "
                    "deleted",
                    node=ev.op,
                    hint="copy() the array before the in-place op, or "
                         "keep MXNET_EAGER_JIT_DONATE=0 while aliases "
                         "are live")
        if len(ev.donated) != len(set(ev.donated)):
            report.emit(
                "GV202",
                f"op '{ev.op}' (event {ev.index}) donates the same "
                "buffer through two argument slots",
                node=ev.op,
                hint="pass distinct arrays for out= and the aliased "
                     "operand")
        for buf in ev.donated:
            dead[buf] = ev


def dead_value_pass(trace, report):
    consumed = set()
    for ev in trace.events:
        consumed.update(ev.inputs)
    for ev in trace.events:
        unused = [b for b in ev.outputs
                  if b not in consumed and b not in trace.live_out]
        if unused and len(unused) == len(ev.outputs):
            report.emit(
                "GV401",
                f"op '{ev.op}' (event {ev.index}) produces "
                f"{len(ev.outputs)} result(s) that nothing consumes",
                node=ev.op,
                hint="remove the dead computation")


TRACE_PASSES = {
    "key_reuse": key_reuse_pass,
    "donation": donation_pass,
    "dead_values": dead_value_pass,
}

DEFAULT_TRACE_PIPELINE = ("key_reuse", "donation", "dead_values")


def verify_trace(trace, passes=None, subject=None):
    """Run the trace passes; returns the (undispositioned) report."""
    report = DiagnosticReport(subject=subject or trace.subject)
    for name in (passes or DEFAULT_TRACE_PIPELINE):
        TRACE_PASSES[name](trace, report)
    return report
