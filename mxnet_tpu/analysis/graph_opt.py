"""Graph-optimization pass manager: analyze-and-REWRITE symbol graphs.

The round-8 verifier (passes.py) walks the DAG and checks; this module
closes the loop the reference closed with nnvm graph passes (reference:
src/nnvm/graph_editor.cc, exec pass registry; Relay/TVM for the
analysis-vs-transform split): the same ``PassContext`` fact cache now
feeds typed **rewrite** passes that return a transformed graph, so the
lowering entry points (``Executor`` bind, ``SymbolBlock``
forward/hybridize, serving ``InferenceSession``) hand XLA a smaller
graph than the user wrote.

Two pass kinds, scheduled by ``PassManager``:

- ``AnalysisPass`` — produces a cached *fact* about the (original)
  graph: shapes, dtypes, op purity/effects, use-counts, reachability.
  Facts are memoized on the ``PassContext`` (one shape inference serves
  verify AND optimize) and never mutate anything.
- ``RewritePass`` — consumes facts, builds an ``old-node -> replacement``
  mapping over the mutable ``_Graph`` work list, and applies it.
  Rewrites never mutate existing ``Symbol`` nodes (graft_lint L601
  enforces this outside ``mxnet_tpu/analysis/``): every change is a
  freshly constructed node; untouched subgraphs are shared by identity.

Shipped rewrite passes, in pipeline order:

``fold``              constant folding: maximal pure const subgraphs
                      (literal ``_sym_zeros``/``_sym_ones``/
                      ``_sym_constant`` roots) are evaluated ONCE at
                      optimize time via the eager op path and replaced
                      by a ``_sym_constant`` literal node.
``cse``               common-subexpression elimination: value numbering
                      over (op, kwargs, attrs, input value-numbers);
                      purity-gated so PRNG/effectful ops never merge.
``transpose_elision`` cancels inverse ``transpose`` pairs (and
                      composes non-inverse pairs into one net
                      permutation), drops identity transposes, and
                      collapses ``reshape``-of-``reshape`` chains when
                      the outer spec is position-independent (all
                      positive dims, at most one -1).
``fusion``            fusion clustering (round 17, analysis/fusion.py):
                      elementwise chains, layer_norm+activation, and
                      score→softmax→weighted-sum attention collapse
                      into single fused ops from ``mxnet_tpu.kernels``
                      when the cost model says the cluster wins; gated
                      by ``MXNET_FUSION`` / ``MXNET_FUSION_PATTERNS``.
``dce``               dead-node elimination: reachability from the
                      heads over the work list; rewrite-orphaned
                      subgraphs (a folded constant's old inputs, a
                      fused cluster's interior) are dropped. Heads
                      always survive — ``grad_req`` outputs are never
                      eliminated.

Gating: ``MXNET_GRAPH_OPT=0`` (default, off) | ``1`` (one sweep) | ``2``
(fixpoint, bounded iterations). Every optimized graph is re-verified
(the cheap verifier passes run as a post-pass); a rewrite that
introduces ANY new error diagnostic is rejected and the original graph
served — the subsystem polices its own output. Counters surface via
``profiler.graph_opt_counters()`` and the ``GRAPH_OPT`` runtime feature.
"""
from __future__ import annotations

import logging
import time

from .passes import FactError, PassContext, register_fact, run_passes
from ..telemetry import metrics as _telemetry
from ..telemetry import tracer as _telem

__all__ = [
    "AnalysisPass", "RewritePass", "PassManager", "PIPELINE_VERSION",
    "DEFAULT_REWRITE_PIPELINE", "REWRITE_PASSES", "opt_level",
    "graph_opt_enabled", "optimize_symbol", "op_is_pure",
    "fingerprint_salt", "counters", "reset_counters",
]

#: version stamp of the rewrite pipeline — part of every compile-cache
#: fingerprint that can see optimized graphs, so optimized and
#: unoptimized artifacts (or artifacts from different pipeline
#: generations) never collide on disk
PIPELINE_VERSION = "graphopt-r19.0"

#: verifier passes run before/after rewriting (no eval_shape: the
#: whole-graph jax.eval_shape cross-check would eat the trace-time win
#: this subsystem exists to produce)
PRE_PASSES = ("shape", "dtype", "structure")

_FOLD_MAX_ELEMENTS = 65536

_key = PassContext.node_key


# ---------------------------------------------------------------------------
# counters (surfaced through profiler.graph_opt_counters; registry-owned
# telemetry families since round 18 — same mutation idiom, scrapeable)

_COUNTERS = _telemetry.counter_family("graph_opt", {
    "graphs_seen": 0, "graphs_optimized": 0, "graphs_rejected": 0,
    "nodes_before_total": 0, "nodes_after_total": 0, "rewrites_total": 0,
    "shape_analysis_runs": 0, "dtype_analysis_runs": 0,
    "fact_cache_hits": 0,
})
# "_"-prefixed: merged into the "graph_opt" probe by counters(), so it
# must not ALSO surface as its own registry family
_PASS_COUNTERS = _telemetry.counter_family("_graph_opt_passes")


def _count(name, n=1):
    _COUNTERS.add(name, n)


def _count_pass(name, rewrites, time_ms):
    _PASS_COUNTERS.add(f"{name}_rewrites", rewrites)
    _PASS_COUNTERS.add(f"{name}_time_ms", time_ms)


def counters():
    """Live optimizer counters: graph totals, per-pass rewrite counts
    and cumulative time, analysis-run/fact-cache tallies."""
    out = _COUNTERS.snapshot()
    out.update((k, round(v, 3) if k.endswith("_time_ms") else v)
               for k, v in sorted(_PASS_COUNTERS.items()))
    return out


def reset_counters():
    _COUNTERS.reset()
    _PASS_COUNTERS.clear()


# ---------------------------------------------------------------------------
# gating

def opt_level():
    """MXNET_GRAPH_OPT clamped to {0, 1, 2}. Read per optimization
    point so tests can toggle without reimport."""
    from .. import env as _env

    return max(0, min(2, _env.get_int("MXNET_GRAPH_OPT", 0)))


def graph_opt_enabled():
    """True when the rewrite pipeline is armed (runtime feature)."""
    return opt_level() > 0


def fingerprint_salt(level=None):
    """Compile-cache key element for graph-opt-aware fingerprints.
    Includes the pipeline version only when optimization is armed, so
    pre-existing level-0 disk entries keep their keys."""
    lvl = opt_level() if level is None else lvl_clamp(level)
    if lvl > 0:
        from .. import kernels

        return ("graph_opt", lvl, PIPELINE_VERSION,
                kernels.fusion_salt())
    return ("graph_opt", 0)


def lvl_clamp(level):
    return max(0, min(2, int(level)))


# ---------------------------------------------------------------------------
# purity / effects analysis

#: ops whose execution draws from the PRNG stream — never folded (the
#: fold would freeze one draw forever) and never CSE-merged (two
#: textually identical dropouts are two independent draws)
_IMPURE_SUBSTRINGS = ("dropout", "random")
_IMPURE_PREFIXES = ("sample_", "_sample", "_random")
_IMPURE_EXACT = {"uniform", "normal", "gamma", "shuffle", "multinomial",
                 "rnn"}

#: ops with observable side effects beyond their outputs (batch_norm
#: folds batch statistics into its aux inputs in training mode) — never
#: folded, never merged
_EFFECTFUL_OPS = {"batch_norm"}


def op_is_pure(op):
    """Conservative purity: False for anything that draws PRNG state or
    carries effects; variables and unknown pure-looking ops are pure."""
    if op is None:
        return True
    low = op.lower()
    if low in _EFFECTFUL_OPS:
        return False
    if any(t in low for t in _IMPURE_SUBSTRINGS):
        return False
    if low.startswith(_IMPURE_PREFIXES):
        return False
    return low not in _IMPURE_EXACT


#: ops that ARE literal constants already (fold sources and fold
#: fixed points: a graph of nothing but these has no fold work left)
_CONST_OPS = {"_sym_zeros", "_sym_ones", "_sym_constant"}


# ---------------------------------------------------------------------------
# the mutable work list rewrite passes operate on

class _Graph:
    """Node work list + heads for one optimization run.

    Unlike ``PassContext.nodes()`` (always re-walked from the symbol),
    the work list persists across rewrites: a rewrite that re-points a
    consumer leaves the orphaned producer chain IN the list, so dead-node
    elimination is an observable, countable pass instead of an implicit
    property of pointer reachability.
    """

    def __init__(self, symbol):
        self.symbol = symbol
        self.heads = list(symbol._group) if symbol._group else [symbol]
        self.nodes = []
        self._keys = set()
        for s in symbol._walk():
            if s._group is not None:
                continue
            k = _key(s)
            if k not in self._keys:
                self._keys.add(k)
                self.nodes.append(s)

    def by_key(self):
        return {_key(n): n for n in self.nodes}

    def apply(self, mapping):
        """Rebuild the work list under ``old-node-key -> replacement``.

        Replacement values: ``None`` removes the node; an existing node
        redirects consumers onto it (CSE, elision-to-input); a fresh
        node (not in the list) is inserted at the replaced position
        with its input references resolved. Kept nodes whose inputs
        changed are cloned (never mutated) — identity is preserved for
        untouched subgraphs. Output views (``__getitem__``) share the
        base node's key; consumer references with ``_output_index > 0``
        are re-viewed off the rebuilt base.
        """
        if not mapping:
            return
        from ..symbol import Symbol

        orig_keys = self._keys
        rebuilt = {}
        new_nodes, present = [], set()

        def resolve_ref(ref):
            r = rebuilt.get(_key(ref))
            if r is None:
                return ref
            if ref._num_outputs > 1 and ref._output_index > 0:
                return r[ref._output_index]
            return r

        def clone_with_inputs(node, new_inputs):
            c = Symbol(op=node._op, name=node._name, inputs=new_inputs,
                       kwargs=dict(node._kwargs),
                       num_outputs=node._num_outputs)
            c._attrs.update(node._attrs)
            return c

        def add(node):
            k = _key(node)
            if k not in present:
                present.add(k)
                new_nodes.append(node)

        for node in self.nodes:
            k = _key(node)
            if k in mapping:
                rep = mapping[k]
                if rep is None:
                    continue  # removed (dce / cse-duplicate)
                if _key(rep) in orig_keys:
                    # existing node (possibly itself rebuilt earlier —
                    # topo order guarantees it was processed already)
                    rebuilt[k] = resolve_ref(rep)
                else:
                    new_inputs = [resolve_ref(i) for i in rep._inputs]
                    if any(a is not b for a, b in
                           zip(new_inputs, rep._inputs)):
                        rep = clone_with_inputs(rep, new_inputs)
                    rebuilt[k] = rep
                    add(rep)
                continue
            if node._op is None:
                add(node)
                continue
            new_inputs = [resolve_ref(i) for i in node._inputs]
            if any(a is not b for a, b in zip(new_inputs, node._inputs)):
                clone = clone_with_inputs(node, new_inputs)
                rebuilt[k] = clone
                add(clone)
            else:
                add(node)

        self.heads = [resolve_ref(h) for h in self.heads]
        self.nodes = new_nodes
        self._keys = present

    def to_symbol(self):
        from ..symbol import Group

        if self.symbol._group is not None:
            return Group(self.heads)
        return self.heads[0]


def _use_counts(graph):
    counts = {}
    for n in graph.nodes:
        for i in n._inputs:
            k = _key(i)
            counts[k] = counts.get(k, 0) + 1
    return counts


def _reachable(graph):
    by_key = graph.by_key()
    live = set()
    stack = list(graph.heads)
    while stack:
        s = stack.pop()
        k = _key(s)
        if k in live:
            continue
        live.add(k)
        stack.extend(by_key.get(k, s)._inputs)
    return live


# ---------------------------------------------------------------------------
# typed passes

class AnalysisPass:
    """A named, cached analysis: ``run(ctx)`` computes the fact once
    per ``PassContext`` and memoizes it (verify-then-optimize analyzes
    the graph once). Registering the instance installs its provider."""

    def __init__(self, name, compute, doc=""):
        self.name = name
        self.doc = doc
        register_fact(name, compute)

    def run(self, ctx):
        return ctx.fact(self.name)


class RewritePass:
    """A named graph transform: ``run(graph, ctx)`` applies a rewrite
    mapping to the work list and returns the rewrite count."""

    def __init__(self, name, fn, doc=""):
        self.name = name
        self.fn = fn
        self.doc = doc

    def run(self, graph, ctx):
        return self.fn(graph, ctx)


def _purity_fact(ctx):
    return {n._op: op_is_pure(n._op) for n in ctx.nodes()
            if n._op is not None}


def _use_counts_fact(ctx):
    return _use_counts(_Graph(ctx.symbol))


def _reachability_fact(ctx):
    return _reachable(_Graph(ctx.symbol))


purity_analysis = AnalysisPass(
    "purity", _purity_fact, "op name -> pure? over the graph's ops")
use_count_analysis = AnalysisPass(
    "use_counts", _use_counts_fact, "node key -> consumer-edge count")
reachability_analysis = AnalysisPass(
    "reachability", _reachability_fact, "node keys reachable from heads")


# ---------------------------------------------------------------------------
# rewrite pass bodies

def _fold_constants(graph, ctx):
    """Evaluate maximal pure constant subgraphs once, via the eager op
    path, and replace each root with a ``_sym_constant`` literal."""
    from .. import autograd

    from ..ndarray import registry as _registry
    from ..symbol import Symbol

    const = {}
    for n in graph.nodes:
        k = _key(n)
        if n._op is None:
            const[k] = False
        elif n._op in _CONST_OPS:
            const[k] = True
        elif not op_is_pure(n._op) or _registry.get_op(n._op) is None:
            const[k] = False
        else:
            const[k] = bool(n._inputs) and all(
                const.get(_key(i), False) for i in n._inputs)

    consumers = {}
    for n in graph.nodes:
        for i in n._inputs:
            consumers.setdefault(_key(i), []).append(n)
    head_keys = {_key(h) for h in graph.heads}

    mapping, eval_cache = {}, {}
    for n in graph.nodes:
        k = _key(n)
        if not const[k] or n._op in _CONST_OPS or n._num_outputs != 1:
            continue
        # fold only MAXIMAL const roots: interior const nodes get
        # orphaned by the root's replacement and fall to dce
        cons = consumers.get(k, ())
        if k not in head_keys and all(const[_key(c)] for c in cons):
            continue
        try:
            import jax

            # ensure_compile_time_eval: optimization may run under an
            # active jit trace (CachedOp / serving _pure); the fold
            # evaluates literal subgraphs, so it must produce CONCRETE
            # arrays even there, never tracers
            with jax.ensure_compile_time_eval():
                with autograd.pause():
                    val = n._eval_nodes({}, eval_cache)
            if isinstance(val, (list, tuple)):
                continue
            arr = val.asnumpy()
        except Exception:
            continue  # an unevaluable candidate is simply not folded
        if arr.size > _FOLD_MAX_ELEMENTS:
            continue
        rep = Symbol(op="_sym_constant", name=n._name, inputs=[],
                     kwargs={"value": arr.tolist(),
                             "shape": tuple(int(d) for d in arr.shape),
                             "dtype": str(arr.dtype)})
        rep._attrs.update(n._attrs)
        mapping[k] = rep
    graph.apply(mapping)
    return len(mapping)


def _cse(graph, ctx):
    """Value numbering over (op, kwargs, attrs, input VNs): later
    occurrences of a computed value re-point at the first. Impure and
    effectful ops get unique value numbers — two dropouts never merge."""
    vn, table, mapping = {}, {}, {}
    counter = 0
    for n in graph.nodes:
        k = _key(n)
        if k in vn:
            continue  # a view's base already numbered
        sig = None
        if n._op is None:
            sig = ("var", n._name)
        elif op_is_pure(n._op):
            try:
                sig = (n._op,
                       repr(sorted(n._kwargs.items())),
                       repr(sorted(n._attrs.items())),
                       tuple((vn[_key(i)], i._output_index)
                             for i in n._inputs),
                       n._num_outputs)
            except KeyError:
                sig = None  # an input outside the work list: unique
        if sig is None:
            vn[k] = counter
            counter += 1
            continue
        hit = table.get(sig)
        if hit is not None:
            prev_vn, rep = hit
            vn[k] = prev_vn
            if n._op is not None and n is not rep:
                mapping[k] = rep
        else:
            vn[k] = counter
            table[sig] = (counter, n)
            counter += 1
    graph.apply(mapping)
    return len(mapping)


def _norm_axes(axes):
    if axes is None or (isinstance(axes, (list, tuple)) and not axes):
        return None
    return tuple(int(a) for a in axes)


def _plain_shape(spec, positive_only=False):
    """A reshape spec free of the MXNet positional codes (0/-2/-3/-4),
    i.e. one whose meaning does not depend on the input shape."""
    if not isinstance(spec, (list, tuple)) or not spec:
        return False
    try:
        dims = [int(d) for d in spec]
    except (TypeError, ValueError):
        return False
    if positive_only:
        return all(d > 0 for d in dims)
    return all(d > 0 or d == -1 for d in dims) and \
        sum(1 for d in dims if d == -1) <= 1


def _transpose_reshape_elision(graph, ctx):
    """Cancel/compose adjacent layout ops: identity transposes,
    transpose-of-transpose (both-None = double full reversal; explicit
    perms composed, identity net dropped), reshape-of-reshape collapse,
    and identity reshapes of variables with known shapes."""
    shapes = ctx.fact("shapes")
    var_shapes = {} if isinstance(shapes, FactError) else shapes[0]

    mapping = {}
    for n in graph.nodes:
        if n._op == "transpose" and n._inputs:
            inp = n._inputs[0]
            q = _norm_axes(n._kwargs.get("axes"))
            if q is not None and q == tuple(range(len(q))):
                mapping[_key(n)] = inp
                continue
            if inp._op != "transpose" or not inp._inputs:
                continue
            p = _norm_axes(inp._kwargs.get("axes"))
            src = inp._inputs[0]
            if p is None and q is None:
                # double full reversal is the identity at any rank
                mapping[_key(n)] = src
            elif p is not None and q is not None and len(p) == len(q):
                net = tuple(p[i] for i in q)
                if net == tuple(range(len(net))):
                    mapping[_key(n)] = src
                else:
                    mapping[_key(n)] = _fresh_like(
                        n, "transpose", [src], {"axes": net})
            # mixed None/explicit: rank unknown here — leave it
        elif n._op == "reshape" and n._inputs:
            if n._kwargs.get("reverse"):
                continue
            spec = n._kwargs.get("shape")
            inp = n._inputs[0]
            if inp._op == "reshape" and inp._inputs \
                    and not inp._kwargs.get("reverse") \
                    and _plain_shape(spec):
                # outer spec is position-independent, inner preserves
                # the element count: collapse to one reshape
                mapping[_key(n)] = _fresh_like(
                    n, "reshape",
                    [inp._inputs[0]],
                    {"shape": tuple(int(d) for d in spec)})
            elif inp._op is None and _plain_shape(spec,
                                                  positive_only=True):
                have = var_shapes.get(inp._name)
                if have is not None and tuple(have) == tuple(
                        int(d) for d in spec):
                    mapping[_key(n)] = inp
    graph.apply(mapping)
    return len(mapping)


def _fresh_like(old, op, inputs, kwargs):
    from ..symbol import Symbol

    rep = Symbol(op=op, name=old._name, inputs=list(inputs),
                 kwargs=kwargs)
    rep._attrs.update(old._attrs)
    return rep


def _dce(graph, ctx):
    """Drop work-list nodes unreachable from the heads. Heads are the
    roots — bound outputs (and their ``grad_req`` gradients) can never
    be eliminated."""
    live = _reachable(graph)
    mapping = {k: None for k in graph._keys if k not in live}
    graph.apply(mapping)
    return len(mapping)


fold_pass = RewritePass("fold", _fold_constants,
                        "constant folding via the eager op path")
cse_pass = RewritePass("cse", _cse,
                       "purity-gated common-subexpression elimination")
transpose_elision_pass = RewritePass(
    "transpose_elision", _transpose_reshape_elision,
    "cancel/compose inverse transpose + reshape chains")
dce_pass = RewritePass("dce", _dce, "dead-node elimination from heads")

REWRITE_PASSES = {p.name: p for p in
                  (fold_pass, cse_pass, transpose_elision_pass, dce_pass)}

DEFAULT_REWRITE_PIPELINE = ("fold", "cse", "transpose_elision",
                            "fusion", "dce")


# ---------------------------------------------------------------------------
# the scheduler

class PassManager:
    """Runs a rewrite pipeline over a ``_Graph``, once (level 1) or to
    a bounded fixpoint (level 2), recording per-pass before/after node
    counts and wall time."""

    #: fixpoint bound: each iteration strictly shrinks the graph or
    #: stops, so this is a safety net, not a tuning knob
    MAX_ITERATIONS = 5

    def __init__(self, passes=None):
        names = passes or DEFAULT_REWRITE_PIPELINE
        self.passes = [p if isinstance(p, RewritePass)
                       else REWRITE_PASSES[p] for p in names]

    def run(self, graph, ctx, fixpoint=False):
        stats, total = [], 0
        iters = self.MAX_ITERATIONS if fixpoint else 1
        for it in range(iters):
            iter_rewrites = 0
            for rp in self.passes:
                before = len(graph.nodes)
                t0 = time.perf_counter()
                with _telem.span(f"graph_opt.{rp.name}", cat="graph_opt",
                                 need=2, iteration=it,
                                 nodes_before=before) as sp:
                    n = rp.run(graph, ctx)
                    sp.set(rewrites=n)
                dt_ms = (time.perf_counter() - t0) * 1e3
                stats.append({
                    "pass": rp.name, "iteration": it,
                    "nodes_before": before,
                    "nodes_after": len(graph.nodes),
                    "rewrites": n, "time_ms": round(dt_ms, 3),
                })
                _count_pass(rp.name, n, dt_ms)
                iter_rewrites += n
            total += iter_rewrites
            if iter_rewrites == 0:
                break
        return total, stats


def optimize_symbol(symbol, shapes=None, dtypes=None, level=None,
                    ctx=None, subject=None, passes=None):
    """Optimize a symbol graph; returns ``(symbol, stats)``.

    ``level`` defaults to ``MXNET_GRAPH_OPT``; 0 is a passthrough. A
    caller-provided ``ctx`` (the bind-time verifier's ``PassContext``)
    shares its fact cache — shape/dtype inference runs once for
    verify-then-optimize. The verifier's cheap passes run before (for
    the error baseline, unless the ctx already ran them) and AFTER on
    the optimized graph: any new error rejects the rewrite and returns
    the original graph.
    """
    lvl = opt_level() if level is None else lvl_clamp(level)
    stats = {"level": lvl, "subject": subject,
             "pipeline_version": PIPELINE_VERSION, "passes": [],
             "nodes_before": None, "nodes_after": None, "rewrites": 0,
             "rejected": False}
    if lvl <= 0:
        return symbol, stats
    _count("graphs_seen")
    with _telem.span("graph_opt.optimize", cat="graph_opt",
                     subject=subject or "graph", level=lvl) as _osp:
        out_symbol, stats = _optimize_inner(symbol, shapes, dtypes, lvl,
                                            ctx, subject, passes, stats)
        _osp.set(rewrites=stats["rewrites"], rejected=stats["rejected"])
        return out_symbol, stats


def _optimize_inner(symbol, shapes, dtypes, lvl, ctx, subject, passes,
                    stats):
    if ctx is None:
        ctx = PassContext(symbol, shapes=shapes, dtypes=dtypes,
                          subject=subject)
    if "shape" not in ctx.passes_run:
        run_passes(ctx, PRE_PASSES)
    pre_errors = len(ctx.report.errors)

    graph = _Graph(symbol)
    stats["nodes_before"] = stats["nodes_after"] = len(graph.nodes)
    total, pass_stats = PassManager(passes).run(graph, ctx,
                                                fixpoint=(lvl >= 2))
    stats["passes"] = pass_stats
    stats["rewrites"] = total
    _count("rewrites_total", total)
    if total == 0:
        return symbol, stats
    stats["nodes_after"] = len(graph.nodes)
    optimized = graph.to_symbol()

    # optimize -> verify, one pipeline: the verifier is the post-pass
    # on every optimized graph
    post_ctx = PassContext(optimized, shapes=shapes, dtypes=dtypes,
                           subject=f"{subject or 'graph'}:optimized")
    run_passes(post_ctx, PRE_PASSES)
    if len(post_ctx.report.errors) > pre_errors:
        logging.warning(
            "graph-opt: rejecting optimized graph for %s (%d new "
            "error diagnostic(s)); serving the original",
            subject or symbol._name,
            len(post_ctx.report.errors) - pre_errors)
        _count("graphs_rejected")
        stats["rejected"] = True
        stats["nodes_after"] = stats["nodes_before"]
        if any(p["pass"] == "fusion" and p["rewrites"]
               for p in pass_stats):
            # the fused graph was among what verify threw out: record
            # the clean fallback on the fusion side too
            from .. import kernels

            kernels._count("fallback_post_verify")
        return symbol, stats
    _count("graphs_optimized")
    _count("nodes_before_total", stats["nodes_before"])
    _count("nodes_after_total", stats["nodes_after"])
    return optimized, stats


# registers the round-17 fusion pass (+ its facts) and the round-19
# int8 quantization passes into REWRITE_PASSES; imported last so the
# pass infra above is complete
from . import fusion  # noqa: E402,F401
from . import quantize  # noqa: E402,F401


# -- artifact-layer salt provider -------------------------------------------
# the "graph_opt" contribution to CompiledArtifact fingerprints: call
# sites declare the name; composition stays here with the pipeline

def _salt_provider(ctx):
    if not ctx.get("optimizable"):
        return ("graph_opt", 0)
    return fingerprint_salt(ctx.get("opt_level"))


from ..artifact import salts as _artifact_salts  # noqa: E402

_artifact_salts.register_salt_provider("graph_opt", _salt_provider)
