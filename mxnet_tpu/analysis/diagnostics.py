"""Structured diagnostics for the static-analysis subsystem.

The analog of the reference's graph-pass error surfaces (reference:
src/executor/infer_graph_attr_pass.cc CHECK failures, src/nnvm/
plan_memory.cc inplace-option vetoes) — but instead of aborting inside a
C++ pass with a stringly CHECK message, every verifier pass emits
``Diagnostic`` records (code, severity, node path, message, fix hint)
into a ``DiagnosticReport``. The report is then *dispositioned* once,
according to ``MXNET_GRAPH_VERIFY``:

- ``0`` (default): verification is off — passes never run;
- ``warn``: diagnostics are logged and counted (profiler counters);
- ``error``: any diagnostic raises ``GraphVerifyError`` carrying the
  full report, so the failure names every problem at once instead of
  dying on the first.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..telemetry import metrics as _telemetry

__all__ = ["Diagnostic", "DiagnosticReport", "GraphVerifyError", "CODES",
           "SEV_ERROR", "SEV_WARNING", "verify_mode", "counters",
           "reset_counters"]

SEV_ERROR = "error"
SEV_WARNING = "warning"

# diagnostic catalogue: code -> (default severity, title)
# GV1xx shape/dtype inference, GV2xx donation/aliasing, GV3xx PRNG,
# GV4xx graph structure, GV5xx sharding.
CODES = {
    "GV101": (SEV_ERROR, "shape mismatch"),
    "GV102": (SEV_ERROR, "dtype mismatch"),
    "GV103": (SEV_ERROR, "shape-inference desync (infer vs eval_shape)"),
    "GV201": (SEV_ERROR, "use-after-donate"),
    "GV202": (SEV_ERROR, "double donation"),
    "GV301": (SEV_ERROR, "PRNG key reuse"),
    "GV401": (SEV_WARNING, "dead node / unused output"),
    "GV402": (SEV_WARNING, "unused input"),
    "GV403": (SEV_ERROR, "duplicate node name"),
    "GV501": (SEV_ERROR, "sharding mismatch"),
    "GV502": (SEV_ERROR, "mesh mismatch"),
    "GV503": (SEV_WARNING, "dead sharding-plan rule"),
}


class Diagnostic:
    """One finding: code + severity + where + what + how to fix."""

    __slots__ = ("code", "severity", "node", "message", "hint")

    def __init__(self, code, message, node=None, hint=None, severity=None):
        if code not in CODES:
            raise ValueError(f"unknown diagnostic code {code!r}")
        self.code = code
        self.severity = severity or CODES[code][0]
        self.node = node  # node path ("fc1/weight"), buffer label, ...
        self.message = message
        self.hint = hint

    def __repr__(self):
        loc = f" at {self.node}" if self.node else ""
        hint = f" (hint: {self.hint})" if self.hint else ""
        return (f"[{self.code} {self.severity}] "
                f"{CODES[self.code][1]}{loc}: {self.message}{hint}")


class GraphVerifyError(MXNetError):
    """Raised in ``MXNET_GRAPH_VERIFY=error`` mode; carries the report."""

    def __init__(self, report):
        self.report = report
        super().__init__("graph verification failed:\n" +
                         "\n".join(f"  {d!r}" for d in report))


class DiagnosticReport:
    """Ordered collection of diagnostics from one verification run."""

    def __init__(self, subject=None):
        self.subject = subject  # what was verified (symbol name, block)
        self._diags = []

    def emit(self, code, message, node=None, hint=None, severity=None):
        self._diags.append(
            Diagnostic(code, message, node=node, hint=hint,
                       severity=severity))
        return self._diags[-1]

    def extend(self, other):
        self._diags.extend(other._diags)

    def __iter__(self):
        return iter(self._diags)

    def __len__(self):
        return len(self._diags)

    def __bool__(self):
        return bool(self._diags)

    def codes(self):
        return [d.code for d in self._diags]

    def by_code(self, code):
        return [d for d in self._diags if d.code == code]

    @property
    def errors(self):
        return [d for d in self._diags if d.severity == SEV_ERROR]

    @property
    def warnings(self):
        return [d for d in self._diags if d.severity == SEV_WARNING]

    def disposition(self, mode=None):
        """Count, then log (warn mode) or raise (error mode). Returns
        self so call sites can chain: ``report = verify(...).disposition()``.
        """
        mode = mode or verify_mode()
        _count(self)
        if mode == "off" or not self._diags:
            return self
        if mode == "error":
            raise GraphVerifyError(self)
        for d in self._diags:
            logging.warning("graph-verify %r", d)
        return self


def verify_mode():
    """MXNET_GRAPH_VERIFY: '0'/off (default) | warn | error. Read per
    verification point so tests can toggle without reimport."""
    from .. import env as _env

    raw = _env.get_str("MXNET_GRAPH_VERIFY", "0").strip().lower()
    if raw in ("", "0", "off", "false", "none"):
        return "off"
    if raw in ("error", "raise", "2"):
        return "error"
    return "warn"  # "warn", "1", anything else conservative-lenient


# ---------------------------------------------------------------------------
# counters (surfaced through profiler.graph_verify_counters;
# registry-owned telemetry families since round 18)

_COUNTERS = _telemetry.counter_family(
    "graph_verify", {"graphs_checked": 0, "diagnostics": 0, "errors": 0,
                     "warnings": 0})
# "_"-prefixed: merged into the "graph_verify" probe by counters()
_BY_CODE = _telemetry.counter_family("_graph_verify_codes")


def _count(report):
    _COUNTERS.add("graphs_checked")
    _COUNTERS.add("diagnostics", len(report))
    _COUNTERS.add("errors", len(report.errors))
    _COUNTERS.add("warnings", len(report.warnings))
    for d in report:
        _BY_CODE.add(d.code)


def counters():
    """Live verifier counters: totals + per-diagnostic-code tallies."""
    out = _COUNTERS.snapshot()
    out.update({f"code_{c}": n for c, n in sorted(_BY_CODE.items())})
    return out


def reset_counters():
    _COUNTERS.reset()
    _BY_CODE.clear()
