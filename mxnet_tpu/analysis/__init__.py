"""mxnet_tpu.analysis — pass-based static analysis for graphs and traces.

A verification layer the reference ran as C++ graph passes at bind time
(reference: src/executor/infer_graph_attr_pass.cc shape/type inference,
src/nnvm/plan_memory.cc in-place/aliasing planning) and Relay-style
typed-IR systems run as whole-program analysis: prove a graph safe
*before* XLA compiles it, with structured diagnostics instead of runtime
trace errors.

Two front ends share one diagnostic catalogue (diagnostics.CODES):

- **Symbol graphs** (``verify_symbol``): shape/dtype inference
  cross-checks, declared-vs-derived parameter shapes, dead outputs,
  duplicate node names. Gated onto ``Executor`` bind by
  ``MXNET_GRAPH_VERIFY={0,warn,error}``.
- **Execution traces** (``record_trace`` + ``verify_trace``): PRNG key
  reuse, use-after-donate, double donation, dead values over one
  recorded eager forward. Gated onto ``HybridBlock.hybridize`` by the
  same knob; the donation checks also run inline in the compiled
  dispatch cache and the fused train-step.

Plus ``verify_shardings`` for the SPMD layer, the runtime donation
guards in ``donation``, and — since round 14 — the **graph_opt rewrite
pipeline** (``optimize_symbol``): the same pass machinery turned from
check-only into analyze-and-rewrite (constant folding, CSE, dead-node
elimination, transpose/reshape elision), gated by
``MXNET_GRAPH_OPT={0,1,2}`` and sharing the verifier's ``PassContext``
fact cache so verify-then-optimize analyzes a graph once. See
docs/ANALYSIS.md for the full catalogue.
"""
from __future__ import annotations

from .diagnostics import (CODES, Diagnostic, DiagnosticReport,
                          GraphVerifyError, SEV_ERROR, SEV_WARNING,
                          counters, reset_counters, verify_mode)
from .donation import check_dispatch_donation, check_param_donation
from .events import (GraphTrace, OpEvent, TRACE_PASSES, record_trace,
                     verify_trace)
from .passes import (FactError, PASSES, PassContext, register_fact,
                     run_passes, verify_symbol)
from .graph_opt import (AnalysisPass, DEFAULT_REWRITE_PIPELINE,
                        PIPELINE_VERSION, PassManager, REWRITE_PASSES,
                        RewritePass, graph_opt_enabled, op_is_pure,
                        opt_level, optimize_symbol)
from .graph_opt import counters as graph_opt_counters
from .graph_opt import fingerprint_salt as graph_opt_fingerprint_salt
from .graph_opt import reset_counters as reset_graph_opt_counters
from .sharding import verify_plan, verify_shardings

__all__ = [
    "CODES", "Diagnostic", "DiagnosticReport", "GraphVerifyError",
    "SEV_ERROR", "SEV_WARNING", "counters", "reset_counters",
    "verify_mode", "check_dispatch_donation", "check_param_donation",
    "GraphTrace", "OpEvent", "TRACE_PASSES", "record_trace",
    "verify_trace", "FactError", "PASSES", "PassContext",
    "register_fact", "run_passes", "verify_symbol",
    "AnalysisPass", "RewritePass", "PassManager", "PIPELINE_VERSION",
    "DEFAULT_REWRITE_PIPELINE", "REWRITE_PASSES", "opt_level",
    "graph_opt_enabled", "optimize_symbol", "op_is_pure",
    "graph_opt_counters", "graph_opt_fingerprint_salt",
    "reset_graph_opt_counters", "verify_shardings", "verify_plan",
    "verify_block_call",
]


def verify_block_call(block, args, subject=None):
    """Verify a (to-be-hybridized) block by recording one paused eager
    forward and running the trace passes. Returns the undispositioned
    report; the hybridize hook dispositions it per MXNET_GRAPH_VERIFY."""
    from .. import autograd
    from .. import random as _mxrandom

    # Finish deferred parameter init FIRST, on the normal stream: the
    # init draws would happen anyway (CachedOp's own throwaway pass runs
    # under the same condition), so their key consumption must persist.
    params = getattr(block, "collect_params", None)
    if params is not None and any(p._ndarray is None
                                  for _, p in params().items()):
        with autograd.pause(train_mode=autograd.is_training()):
            block.forward(*args)
    # The verification forward itself is THROWAWAY: restore the global
    # PRNG stream (arming MXNET_GRAPH_VERIFY must never shift the keys
    # the real run draws) AND every parameter buffer (a training-mode
    # forward folds fresh batch stats into BatchNorm running mean/var —
    # without the restore the first real step would apply that EMA
    # twice). Seeded runs stay byte-identical with verification on/off.
    saved_key = _mxrandom._STATE.key
    saved_params = []
    if params is not None:
        saved_params = [(p._ndarray, p._ndarray._data)
                        for _, p in params().items()
                        if p._ndarray is not None]
    try:
        with record_trace(subject=subject or type(block).__name__) as trace:
            with autograd.pause(train_mode=autograd.is_training()):
                out = block.forward(*args)
    finally:
        _mxrandom._STATE.key = saved_key
        for nd_obj, data in saved_params:
            nd_obj._data = data
    outs = out if isinstance(out, (list, tuple)) else [out]
    flat = []
    for o in outs:
        flat.extend(o if isinstance(o, (list, tuple)) else [o])
    trace.mark_outputs(flat)
    return verify_trace(trace)
