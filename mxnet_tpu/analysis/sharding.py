"""Sharding-consistency checks for the SPMD layer.

GSPMD accepts almost any sharding and silently falls back to
replication-with-reshards when a spec doesn't divide a dimension — the
program still runs, just slower, and the asymmetry is invisible until a
profile. These checks make the contract explicit at bind time
(reference analog: the reference validated device placement eagerly in
``DataParallelExecutorGroup`` — batch size divisible by the ctx list,
executor_group.py:282):

- GV502: shardings built against different Mesh objects mixed in one
  program (collectives would disagree on the axis universe);
- GV501: a PartitionSpec naming an axis the mesh doesn't have, a dim
  index out of range for the array's rank, or a sharded dimension not
  divisible by the product of its mesh axis sizes.

``verify_plan`` runs the same GV501 checks over a ``ShardingPlan``'s
RAW rule resolutions (before the plan's runtime divisibility fallback
rewrites them to replication), plus:

- GV503: a plan rule whose pattern matches none of the given names —
  a dead rule is almost always a typo'd regex silently replicating the
  tensors it meant to shard.
"""
from __future__ import annotations

from .diagnostics import DiagnosticReport

__all__ = ["verify_shardings", "verify_plan"]


def _spec_entries(spec):
    """PartitionSpec -> list of (dim, (axis names...)) for sharded dims."""
    out = []
    for dim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        out.append((dim, tuple(axes)))
    return out


def verify_shardings(shapes, shardings, mesh=None, subject=None):
    """Check {name: shape} against {name: NamedSharding | PartitionSpec}.

    Raw ``PartitionSpec`` values are checked against ``mesh`` (required
    for them) — this is what lets ``shard_params`` validate user rules
    *before* ``NamedSharding`` construction turns a bad axis name into a
    bare ValueError. ``mesh`` otherwise pins the expected mesh; with
    NamedShardings and no ``mesh``, the first sharding's mesh is the
    reference. Returns the (undispositioned) DiagnosticReport.
    """
    report = DiagnosticReport(subject=subject or "shardings")
    ref_mesh = mesh
    for name in shardings:
        sh = shardings[name]
        this_mesh = getattr(sh, "mesh", None)
        spec = getattr(sh, "spec", sh)  # NamedSharding or raw spec
        if this_mesh is None:
            this_mesh = ref_mesh
            if this_mesh is None:
                continue  # raw spec without a mesh: nothing to check
        if ref_mesh is None:
            ref_mesh = this_mesh
        elif this_mesh is not ref_mesh and \
                dict(getattr(this_mesh, "shape", {})) != \
                dict(getattr(ref_mesh, "shape", {})):
            report.emit(
                "GV502",
                f"'{name}' is sharded over mesh "
                f"{dict(this_mesh.shape)} but the program's mesh is "
                f"{dict(ref_mesh.shape)}",
                node=name,
                hint="build every sharding from the same make_mesh() "
                     "result")
            continue
        shape = shapes.get(name)
        if shape is None:
            continue
        shape = tuple(shape)
        axis_sizes = dict(this_mesh.shape)
        for dim, axes in _spec_entries(spec):
            unknown = [a for a in axes if a not in axis_sizes]
            if unknown:
                report.emit(
                    "GV501",
                    f"'{name}' dim {dim} is sharded over axis "
                    f"{unknown[0]!r} but the mesh axes are "
                    f"{sorted(axis_sizes)}",
                    node=name,
                    hint="fix the PartitionSpec axis name")
                continue
            if dim >= len(shape):
                report.emit(
                    "GV501",
                    f"'{name}' has rank {len(shape)} but its "
                    f"PartitionSpec shards dim {dim}",
                    node=name,
                    hint="the spec has more entries than the array has "
                         "dimensions")
                continue
            total = 1
            for a in axes:
                total *= axis_sizes[a]
            if total and shape[dim] % total != 0:
                report.emit(
                    "GV501",
                    f"'{name}' dim {dim} has size {shape[dim]}, not "
                    f"divisible by the {'x'.join(axes)} mesh extent "
                    f"{total}",
                    node=name,
                    hint=f"pad dim {dim} to a multiple of {total} or "
                         "reshape the mesh")
    return report


def verify_plan(plan, named_shapes, mesh, subject=None):
    """Static plan-vs-mesh check for a ``sharding.ShardingPlan``.

    Resolves every name's RAW matched spec (no divisibility fallback,
    no scalar shortcut) and runs the GV501 axis/rank/divisibility
    checks against ``mesh`` — exactly the mismatches the runtime
    fallback would silently paper over with replication — then flags
    rules that matched nothing (GV503). Returns the undispositioned
    DiagnosticReport, like ``verify_shardings``.
    """
    report = DiagnosticReport(subject=subject or "sharding plan")
    named_shapes = {n: tuple(s) for n, s in named_shapes.items()}
    raw = {}
    hits = set()
    for name, shape in named_shapes.items():
        hit = plan.match(name)
        if hit is None:
            continue
        hits.add(hit[0])
        raw[name] = _entries_to_spec(hit[1])
    report.extend(verify_shardings(named_shapes, raw, mesh=mesh,
                                   subject=subject or "sharding plan"))
    for pat, _spec in plan.rules:
        if pat not in hits:
            report.emit(
                "GV503",
                f"plan rule {pat!r} matches none of the "
                f"{len(named_shapes)} given names",
                node=pat,
                hint="dead rules usually mean a typo'd regex — the "
                     "tensors it meant to shard are replicating")
    return report


def _entries_to_spec(entries):
    """Plan-canonical entry tuple -> a PartitionSpec-like tuple that
    ``_spec_entries`` understands (kept here so analysis does not
    import jax.sharding)."""
    return tuple(None if e is None else tuple(e) for e in entries)
