"""Fusion clustering: group fusable subgraphs into kernels-package ops.

The round-17 rewrite pass. XLA fuses well *inside* one compiled
program, but every graph node costs one dispatch on the eager /
serving paths, and XLA's automatic fusion still splits around
reductions ("Operator Fusion in XLA: Analysis and Evaluation",
PAPERS.md). This pass pattern-matches three cluster kinds over the
``_Graph`` work list —

- **elementwise** maximal chains/trees of pure, single-consumer
  elementwise ops (``kernels.elementwise.ELEMENTWISE_OPS``),
- **norm_act** ``layer_norm`` feeding one activation node
  (BatchNorm→act is matched but always rejected: ``batch_norm`` is
  effectful through the aux-state machinery — counted as
  ``fallback_effectful``),
- **attention** ``batch_dot(softmax(batch_dot(q, k, T) [*/ scale]),
  v)`` score→softmax→weighted-sum,

— and replaces each profitable cluster with ONE fused op from
``mxnet_tpu.kernels``. Profitability and implementation (``lax``
replay everywhere, ``pallas`` on TPU when shapes meet the tile floor)
are decided per-cluster by ``kernels.cost_model.decide``; rejected
candidates keep their 1:1 lowering and the reason lands in the
fusion counters. A bad fused kernel is caught by ``optimize_symbol``'s
post-verify, which falls the whole graph back to the original (the
round-14 rejection safety net, counted as ``fallback_post_verify``).

Pattern classification and per-node shapes are memoized ``PassContext``
facts (``fusion_patterns``, ``node_shapes``) — verify-then-optimize
and fixpoint iterations classify each original node once.
"""
from __future__ import annotations

from .graph_opt import (REWRITE_PASSES, AnalysisPass, RewritePass,
                        _fresh_like, _key, _use_counts, op_is_pure)
from .passes import FactError

#: activation-op defaults, needed to resolve the effective act_type of
#: a matched activation node (replay passes the node kwargs verbatim,
#: so defaults only matter for *matching*)
_ACT_DEFAULTS = {"activation": "relu", "leaky_relu": "leaky"}

_SCALE_OPS = {"broadcast_mul_scalar": "mul", "broadcast_div_scalar": "div"}


class _Unfreezable(Exception):
    pass


def _freeze(v):
    """Kwarg value -> hashable, repr-stable form (tuples for lists);
    raises _Unfreezable for anything a static jit kwarg can't carry."""
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, (str, int, float, bool, type(None))):
        return v
    try:
        hash(v)
    except TypeError:
        raise _Unfreezable from None
    return v


def _frozen_kwargs(node):
    """``node._kwargs`` as a sorted, hashable items tuple, or None when
    any value resists freezing (such a node is never absorbed)."""
    try:
        return tuple((k, _freeze(v))
                     for k, v in sorted(node._kwargs.items()))
    except _Unfreezable:
        return None


# ---------------------------------------------------------------------------
# memoized facts

def _classify(node):
    """Pattern role of one node, or None. Pure classification — no
    use-count/head checks here (those are graph-state, not node-state)."""
    from ..kernels.elementwise import ELEMENTWISE_OPS
    from ..kernels.norm_act import FUSABLE_ACTS

    op = node._op
    if op is None or node._num_outputs != 1 or not op_is_pure(op):
        return "bn_act_candidate" if op == "batch_norm" else None
    roles = []
    if op in ELEMENTWISE_OPS:
        roles.append("elementwise")
    if op in FUSABLE_ACTS:
        eff = node._kwargs.get("act_type", _ACT_DEFAULTS.get(op))
        if eff in FUSABLE_ACTS[op]:
            roles.append("act")
    if op == "layer_norm" and not node._kwargs.get("output_mean_var"):
        roles.append("norm")
    if op == "batch_dot":
        roles.append("batch_dot")
    if op == "softmax":
        roles.append("softmax")
    if op in _SCALE_OPS and not node._kwargs.get("reverse"):
        roles.append("scale")
    return tuple(roles) or None


def _fusion_patterns_fact(ctx):
    """node key -> role tuple over the original graph (memoized; the
    rewrite re-classifies only nodes other passes created later)."""
    out = {}
    for n in ctx.nodes():
        out[_key(n)] = _classify(n)
    return out


def _node_shapes_fact(ctx):
    """node key -> inferred output shape (memoized). Rides the same
    walk as ``infer_shapes`` with the per-node table kept, so the cost
    model can price clusters; unknown shapes simply price as None."""
    from ..symbol.infer import infer_shapes

    known = dict(ctx.declared_shapes())
    known.update(ctx.known_shapes)
    try:
        _, _, node_out = infer_shapes(ctx.symbol, known,
                                      allow_unknown=True,
                                      return_node_shapes=True)
    except Exception:
        return FactError("node shape inference failed")
    by_id = {id(n): n for n in ctx.nodes()}
    return {_key(n): node_out[i] for i, n in by_id.items()
            if i in node_out}


fusion_pattern_analysis = AnalysisPass(
    "fusion_patterns", _fusion_patterns_fact,
    "node key -> fusion pattern roles")
node_shape_analysis = AnalysisPass(
    "node_shapes", _node_shapes_fact,
    "node key -> inferred output shape (for the fusion cost model)")


# ---------------------------------------------------------------------------
# the rewrite

def _roles(node, fact):
    k = _key(node)
    if k in fact:
        return fact[k] or ()
    return _classify(node) or ()  # node created by an earlier rewrite


def _shape_of(node, shapes):
    if isinstance(shapes, (FactError, type(None))):
        return None
    s = shapes.get(_key(node))
    if isinstance(s, list):
        s = s[node._output_index] if node._output_index < len(s) else None
    return s


def _plain_softmax(node):
    """True for softmax over the last axis with none of the masking /
    temperature / dtype extras (those change the replay contract)."""
    kw = node._kwargs
    return (len(node._inputs) == 1
            and kw.get("axis", -1) == -1
            and not kw.get("use_length")
            and kw.get("temperature") in (None, 1.0)
            and kw.get("dtype") is None)


def _fusion(graph, ctx):
    """The clustering rewrite body: match → cost-model → replace."""
    import jax

    from .. import kernels
    from ..kernels import cost_model

    if not kernels.fusion_enabled():
        kernels._count("pass_skipped_disabled")
        return 0
    patterns = kernels.enabled_patterns()
    mode = kernels.cost_model_mode()
    backend = jax.default_backend()
    fact = ctx.fact("fusion_patterns")
    shapes = ctx.fact("node_shapes")
    use_counts = _use_counts(graph)
    head_keys = {_key(h) for h in graph.heads}
    order = {_key(n): i for i, n in enumerate(graph.nodes)}

    consumed = set()
    mapping = {}
    clusters = 0

    def interior_ok(node):
        """May ``node`` be absorbed as a cluster interior? Single
        consumer, not a graph output, single-output, in the work
        list."""
        k = _key(node)
        return (k in order and k not in consumed and k not in head_keys
                and use_counts.get(k, 0) == 1 and node._num_outputs == 1
                and node._output_index == 0)

    def decide(pattern, members, root, score_shape=None):
        d = cost_model.decide(pattern, len(members),
                              out_shape=_shape_of(root, shapes),
                              backend=backend, mode=mode,
                              score_shape=score_shape)
        if d.fuse:
            kernels._count(f"clusters_{pattern}")
            kernels._count(f"impl_{d.impl}")
            kernels._count("nodes_absorbed", len(members) - 1)
        else:
            kernels._count(f"fallback_{d.reason}")
        return d

    def claim(members, root_key, fused):
        nonlocal clusters
        consumed.update(_key(m) for m in members)
        mapping[root_key] = fused
        clusters += 1

    # -- attention: most specific first ---------------------------------
    if "attention" in patterns:
        for n in reversed(graph.nodes):
            k = _key(n)
            if k in consumed or "batch_dot" not in _roles(n, fact):
                continue
            if n._kwargs.get("transpose_a") or \
                    n._kwargs.get("transpose_b") or len(n._inputs) != 2:
                continue
            p, v = n._inputs
            if "softmax" not in _roles(p, fact) or not interior_ok(p) \
                    or not _plain_softmax(p):
                continue
            s = p._inputs[0]
            scale_op, scale = "none", 1.0
            if s._op in _SCALE_OPS and interior_ok(s) \
                    and "scale" in _roles(s, fact):
                scale_op = _SCALE_OPS[s._op]
                scale = float(s._kwargs.get("scalar", 0.0))
                score = s._inputs[0]
            else:
                s, score = None, s
            if "batch_dot" not in _roles(score, fact) \
                    or not interior_ok(score):
                continue
            if score._kwargs.get("transpose_a") \
                    or not score._kwargs.get("transpose_b") \
                    or len(score._inputs) != 2:
                continue
            members = [score, p, n] + ([s] if s is not None else [])
            softmax_kw = _frozen_kwargs(p)
            if softmax_kw is None:
                continue
            d = decide("attention", members, n,
                       score_shape=_shape_of(score, shapes))
            if not d.fuse:
                continue
            q, kk = score._inputs
            claim(members, k, _fresh_like(n, "_fused_attention",
                                          [q, kk, v],
                                          {"scale_op": scale_op,
                                           "scale": scale,
                                           "softmax_kw": softmax_kw,
                                           "impl": d.impl}))

    # -- norm + activation ----------------------------------------------
    if "norm_act" in patterns:
        for n in reversed(graph.nodes):
            k = _key(n)
            if k in consumed or "act" not in _roles(n, fact):
                continue
            if len(n._inputs) != 1:
                continue  # prelu-style parameterized acts stay out
            ln = n._inputs[0]
            if "bn_act_candidate" in _roles(ln, fact):
                # the pattern the issue names, rejected by design:
                # batch_norm's running-stat write-back must survive
                kernels._count("fallback_effectful")
                continue
            if "norm" not in _roles(ln, fact) or not interior_ok(ln):
                continue
            if len(ln._inputs) != 3:
                continue
            members = [ln, n]
            norm_kw = _frozen_kwargs(ln)
            act_kw = _frozen_kwargs(n)
            if norm_kw is None or act_kw is None:
                continue
            d = decide("norm_act", members, n)
            if not d.fuse:
                continue
            claim(members, k, _fresh_like(n, "_fused_norm_act",
                                          list(ln._inputs),
                                          {"norm_kw": norm_kw,
                                           "act_op": n._op,
                                           "act_kw": act_kw,
                                           "impl": d.impl}))

    # -- elementwise chains/trees ---------------------------------------
    if "elementwise" in patterns:
        for n in reversed(graph.nodes):
            k = _key(n)
            if k in consumed or "elementwise" not in _roles(n, fact):
                continue
            if _frozen_kwargs(n) is None:
                continue
            members, frontier = [n], list(n._inputs)
            member_keys = {k}
            while frontier:
                cand = frontier.pop()
                ck = _key(cand)
                if ck in member_keys:
                    continue
                if "elementwise" in _roles(cand, fact) \
                        and interior_ok(cand) \
                        and _frozen_kwargs(cand) is not None:
                    member_keys.add(ck)
                    members.append(cand)
                    frontier.extend(cand._inputs)
            if len(members) < 2:
                kernels._count("fallback_too_small")
                continue
            d = decide("elementwise", members, n)
            if not d.fuse:
                continue
            fused = _build_elementwise(members, member_keys, n, order)
            if fused is None:
                continue
            claim(members, k, fused)

    graph.apply(mapping)
    return clusters


def _build_elementwise(members, member_keys, root, order):
    """Emit the ``_fused_elementwise`` replacement for one chain/tree:
    topo-sort the members, collect external inputs (first-seen order),
    and serialize each member as a ``(op, arg_slots, kw_items)`` step
    over the slot file."""
    members = sorted(members, key=lambda m: order.get(_key(m), 1 << 30))
    ext, ext_slot = [], {}
    # slot of each member's result, assigned as steps are emitted
    member_slot = {}
    steps = []
    for m in members:
        arg_slots = []
        for i in m._inputs:
            ik = _key(i)
            if ik in member_keys and i._output_index == 0:
                arg_slots.append(("m", ik))
            else:
                ek = (ik, i._output_index)
                if ek not in ext_slot:
                    ext_slot[ek] = len(ext)
                    ext.append(i)
                arg_slots.append(("e", ext_slot[ek]))
        steps.append((m, arg_slots))
    n_ext = len(ext)
    program = []
    for j, (m, arg_slots) in enumerate(steps):
        resolved = []
        for tag, val in arg_slots:
            if tag == "m":
                if val not in member_slot:
                    return None  # member used before computed: bail
                resolved.append(member_slot[val])
            else:
                resolved.append(val)
        kw = _frozen_kwargs(m)
        program.append((m._op, tuple(resolved), kw))
        member_slot[_key(m)] = n_ext + j
    return _fresh_like(root, "_fused_elementwise", ext,
                       {"program": tuple(program)})


fusion_pass = RewritePass(
    "fusion", _fusion,
    "cluster fusable subgraphs into kernels-package fused ops")
REWRITE_PASSES["fusion"] = fusion_pass
