"""Int8 quantization as graph rewrite passes (reference:
src/operator/quantization/quantize_graph_pass.cc QuantizeGraph; TVM/Relay
frame the same transform as insert -> calibrate -> partition passes over
a typed IR).

The round-14 pass manager turned `contrib/quantization.py`'s monolithic
region-growing rewrite into three composable passes scheduled by
``optimize_symbol`` — which buys the int8 path the post-verify rejection
net for free: a rewrite that introduces any new error diagnostic is
thrown away and the fp32 graph served.

``quantize_insert``     wraps every quantizable op in its own int8
                        island: ``quantize_v2`` on each data input, the
                        ``_contrib_quantized_*`` op, ``requantize`` for
                        int32-accumulating ops (conv / fully_connected /
                        batch_dot), and a trailing ``dequantize`` back to
                        fp32. Conv/fc weights become offline-quantized
                        variables (or weight-scale CONSTANTS when the
                        caller provides parameter values).
``quantize_elide``      merges adjacent islands: a ``quantize_v2`` whose
                        data input is the ``dequantize`` of a producer's
                        (q, min, max) triple re-points its consumers at
                        the producer triple directly, so int8 regions
                        never bounce through fp32 at interior edges.
                        Gated on every consumer being quantization-aware
                        — elision across a non-quantized consumer never
                        fires. uint8/int8 lattice mismatches at merged
                        edges are resolved IN-OP (``_to_s8_lattice`` in
                        ndarray/ops_quant.py), which is what lets the
                        elision ignore payload dtype.
``quantize_calibrate``  folds calibration statistics into the graph:
                        surviving boundary ``quantize_v2`` /
                        ``requantize`` / quantized-BN nodes get
                        ``min/max_calib_range`` kwargs from the
                        calibration table (auto mode upgrades provably
                        non-negative flexible boundaries to the uint8
                        lattice), and every statically-known range
                        output is re-pointed to a ``_sym_constant``
                        scalar so downstream scale math constant-folds.

Pipeline order matters: elide BEFORE calibrate, so calibration only
decorates the boundaries that survive merging — interior ranges of ops
whose output lattice is runtime-derived (elemwise_add, concat) are never
overwritten with table constants that describe a different lattice.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

from .passes import PassContext
from ..telemetry import metrics as _telemetry

__all__ = [
    "QUANTIZE_PIPELINE", "QUANTIZED_OPS", "quantize_scope",
    "current_scope", "fingerprint_salt", "counters", "reset_counters",
]

_key = PassContext.node_key

#: fp32 op -> quantized-lattice op (reference: quantize_graph_pass.cc
#: the per-op NeedQuantize table). batch_dot is new in round 19 — both
#: operands are activations, so it quantizes without offline weights.
QUANTIZED_OPS = {
    "convolution": "_contrib_quantized_conv",
    "fully_connected": "_contrib_quantized_fully_connected",
    "batch_dot": "_contrib_quantized_batch_dot",
    "pooling": "_contrib_quantized_pooling",
    "activation": "_contrib_quantized_act",
    "flatten": "_contrib_quantized_flatten",
    "elemwise_add": "_contrib_quantized_elemwise_add",
    "concat": "_contrib_quantized_concat",
    "batch_norm": "_contrib_quantized_batch_norm",
}

#: int32-accumulating quantized ops: their islands end in `requantize`
_ACC_OPS = {"convolution", "fully_connected", "batch_dot"}

#: quantized ops whose payload output is already int8/uint8 (NOT the
#: int32 accumulators) — valid elision producers
_LATTICE_OUT_OPS = {"quantize", "quantize_v2", "requantize"} | {
    v for k, v in QUANTIZED_OPS.items() if k not in _ACC_OPS}

#: ops allowed to consume a (q, min, max) triple — elision only fires
#: when every consumer of the quantize node is in this set
_TRIPLE_CONSUMERS = {"requantize", "dequantize"} | set(
    QUANTIZED_OPS.values())


# ---------------------------------------------------------------------------
# counters

_COUNTERS = _telemetry.counter_family("quantize", {
    "graphs_quantized": 0, "nodes_quantized": 0, "islands_elided": 0,
    "nodes_calibrated": 0, "scales_folded": 0, "uint8_boundaries": 0,
    "weight_bytes_saved": 0, "kv_pages_quantized": 0,
})


def _count(name, n=1):
    _COUNTERS.add(name, n)


def counters():
    """Live quantization-pass counters: islands formed/merged, scale
    constants folded, estimated weight bytes saved by int8 storage."""
    return _COUNTERS.snapshot()


def reset_counters():
    _COUNTERS.reset()


# ---------------------------------------------------------------------------
# the scope rewrite passes read their configuration from

class QuantizeScope:
    """Per-run configuration + results for the quantize pipeline.

    The pass bodies are stateless functions scheduled by the pass
    manager; everything run-specific (exclusions, the calibration
    table, parameter values for offline weight quantization) travels
    here. ``offline`` and ``meta`` are OUTPUTS: the wrapper in
    contrib/quantization.py reads them after ``optimize_symbol``.
    """

    def __init__(self, excluded_sym_names=(), excluded_op_names=(),
                 calib_ranges=None, auto_dtype=False):
        self.excluded_sym_names = set(excluded_sym_names)
        self.excluded_op_names = set(excluded_op_names)
        self.calib_ranges = dict(calib_ranges or {})
        self.auto_dtype = bool(auto_dtype)
        #: weight var -> (quantized_name, min_name, max_name) variables
        #: the caller populates (reference: offline_params)
        self.offline = {}
        #: node name -> {"src": tensor name, "flex": bool} for nodes the
        #: insertion pass created; keyed by NAME because graph.apply
        #: clones preserve names while node identity churns
        self.meta = {}
        #: int8 islands the insertion pass formed (0 = nothing in the
        #: graph was quantizable under the exclusions)
        self.islands = 0


_tls = threading.local()


def current_scope():
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


@contextmanager
def quantize_scope(**kwargs):
    scope = QuantizeScope(**kwargs)
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(scope)
    try:
        yield scope
    finally:
        stack.pop()


# ---------------------------------------------------------------------------
# helpers

def _out_name(s):
    outs = s.list_outputs()
    return outs[s._output_index if s._num_outputs > 1 else 0]


def _view(base, ref):
    """Re-view ``base`` at ``ref``'s output index (identity for
    single-output nodes and index 0)."""
    if ref._num_outputs > 1 and ref._output_index > 0:
        return base[ref._output_index]
    return base


def _rebuild(graph, new_heads):
    """Wholesale work-list rebuild from fresh heads. The insertion pass
    creates multi-node chains (quantize -> op -> requantize ->
    dequantize); ``_Graph.apply`` only resolves a replacement's direct
    inputs, so interior chain nodes would never join the work list —
    a fresh walk keeps every later pass able to see them."""
    from ..symbol import Group

    graph.heads = list(new_heads)
    graph.nodes = []
    graph._keys = set()
    for s in Group(new_heads)._walk():
        if s._group is not None:
            continue
        k = _key(s)
        if k not in graph._keys:
            graph._keys.add(k)
            graph.nodes.append(s)


def _quantizable(node, scope):
    if node._op not in QUANTIZED_OPS:
        return False
    if (node._name or "") in scope.excluded_sym_names:
        return False
    if node._op in scope.excluded_op_names:
        return False
    kw = node._kwargs
    if node._op == "activation" and kw.get("act_type") != "relu":
        return False
    if node._op == "pooling" and kw.get("pool_type", "max") not in (
            "max", "avg"):
        return False
    if node._op == "batch_norm" and (
            kw.get("output_mean_var") or kw.get("axis", 1) != 1):
        return False  # quantized BN is wired for channel axis 1
    if node._op in ("convolution", "fully_connected") and \
            node._inputs[1]._op is not None:
        return False  # weight is computed, cannot quantize offline
    return True


# ---------------------------------------------------------------------------
# pass 1: insertion

def _quantize_insert(graph, ctx):
    """Wrap each quantizable op in a per-node int8 island. Merging the
    islands is ``quantize_elide``'s job — keeping insertion per-node
    makes every boundary an explicit, testable dequant->quant pair."""
    scope = current_scope()
    if scope is None:
        return 0
    from ..symbol import Symbol, _make_node, var as _svar

    rep = {}     # original base key -> new fp32 base node
    qmemo = {}   # (input base key, out idx, req) -> (q, mn, mx)
    created = 0

    def fp32_of(ref):
        base = rep.get(_key(ref))
        if base is None or base is ref:
            return ref
        return _view(base, ref)

    def as_q(ref, req):
        nonlocal created
        idx = ref._output_index if ref._num_outputs > 1 else 0
        mkey = (_key(ref), idx, req)
        hit = qmemo.get(mkey)
        if hit is not None:
            return hit
        name = (ref._name or "t") + f"_quantize_{req}{idx}"
        n = _make_node("quantize_v2", [fp32_of(ref)],
                       {"out_type": "int8"}, name=name)
        scope.meta[name] = {"src": _out_name(ref), "flex": req != "int8"}
        created += 1
        triple = (n[0], n[1], n[2])
        qmemo[mkey] = triple
        return triple

    def weight_vars(wnode):
        """Offline-quantized weight: three fresh variables the caller
        fills from the fp32 params (reference: offline_params). Tied
        weights hit the memo and share one variable set."""
        wname = wnode._name
        if wname not in scope.offline:
            scope.offline[wname] = (wname + "_quantized",
                                    wname + "_min", wname + "_max")
        qn, mnn, mxn = scope.offline[wname]
        return _svar(qn), _svar(mnn), _svar(mxn)

    islands = 0
    for node in list(graph.nodes):
        k = _key(node)
        if node._op is None:
            rep[k] = node
            continue
        if not _quantizable(node, scope):
            ins = [fp32_of(i) for i in node._inputs]
            if all(a is b for a, b in zip(ins, node._inputs)):
                rep[k] = node
            else:
                newn = Symbol(op=node._op, name=node._name, inputs=ins,
                              kwargs=dict(node._kwargs),
                              num_outputs=node._num_outputs)
                newn._attrs.update(node._attrs)
                rep[k] = newn
            continue
        op, name, kw = node._op, node._name, dict(node._kwargs)
        if op in ("convolution", "fully_connected"):
            dq, dmn, dmx = as_q(node._inputs[0], "int8")
            wq, wmn, wmx = weight_vars(node._inputs[1])
            ins = [dq, wq, dmn, dmx, wmn, wmx]
            if len(node._inputs) > 2 and not kw.get("no_bias"):
                ins.append(fp32_of(node._inputs[2]))
            qn = _make_node(QUANTIZED_OPS[op], ins, kw,
                            name="quantized_" + name)
        elif op == "batch_dot":
            lq, lmn, lmx = as_q(node._inputs[0], "int8")
            rq, rmn, rmx = as_q(node._inputs[1], "int8")
            qn = _make_node(QUANTIZED_OPS[op],
                            [lq, rq, lmn, lmx, rmn, rmx], kw,
                            name="quantized_" + name)
        elif op == "batch_norm":
            dq, dmn, dmx = as_q(node._inputs[0], "any")
            gamma, beta, mean, var_ = (fp32_of(i)
                                       for i in node._inputs[1:5])
            bkw = {"eps": kw.get("eps", 1e-3),
                   "fix_gamma": kw.get("fix_gamma", True)}
            qn = _make_node(QUANTIZED_OPS[op],
                            [dq, gamma, beta, mean, var_, dmn, dmx],
                            bkw, name="quantized_" + name)
            scope.meta["quantized_" + name] = {"src": _out_name(node),
                                               "flex": False}
        elif op == "elemwise_add":
            lq, lmn, lmx = as_q(node._inputs[0], "any")
            rq, rmn, rmx = as_q(node._inputs[1], "any")
            qn = _make_node(QUANTIZED_OPS[op],
                            [lq, rq, lmn, lmx, rmn, rmx], {},
                            name="quantized_" + name)
        elif op == "concat":
            qs = [as_q(i, "any") for i in node._inputs]
            ins = [q for q, _, _ in qs] + [mn for _, mn, _ in qs] + \
                [mx_ for _, _, mx_ in qs]
            qn = _make_node(QUANTIZED_OPS[op], ins,
                            {"dim": kw.get("dim", 1)},
                            name="quantized_" + name)
        else:  # pooling / activation / flatten: data + range through
            dq, dmn, dmx = as_q(node._inputs[0], "any")
            qn = _make_node(QUANTIZED_OPS[op], [dq, dmn, dmx], kw,
                            name="quantized_" + name)
        if op in _ACC_OPS:
            rq_ = _make_node("requantize", [qn[0], qn[1], qn[2]],
                             {"out_type": "int8"},
                             name=name + "_requantize")
            scope.meta[name + "_requantize"] = {"src": _out_name(node),
                                                "flex": False}
            qn = rq_
        deq = _make_node("dequantize", [qn[0], qn[1], qn[2]], {},
                         name=name + "_dequantize")
        rep[k] = deq
        islands += 1
        created += 1

    scope.islands = islands
    if islands == 0:
        return 0
    _rebuild(graph, [fp32_of(h) for h in graph.heads])
    _count("graphs_quantized")
    _count("nodes_quantized", islands)
    return created


# ---------------------------------------------------------------------------
# pass 2: dequant->quant elision

def _quantize_elide(graph, ctx):
    """Merge adjacent int8 islands: ``quantize_v2(dequantize(q, mn, mx))``
    where (q, mn, mx) are the 0/1/2 output views of one lattice-output
    producer re-points consumers straight at the producer triple. The
    dequantize survives if anything fp32 still reads it (DCE collects it
    otherwise), and the rewrite never fires when the quantize node has a
    consumer that is not quantization-aware."""
    consumers = {}
    for n in graph.nodes:
        for i in n._inputs:
            consumers.setdefault(_key(i), []).append(n)
    head_keys = {_key(h) for h in graph.heads}

    mapping = {}
    for n in graph.nodes:
        if n._op not in ("quantize_v2", "quantize"):
            continue
        k = _key(n)
        if k in head_keys:
            continue
        d = n._inputs[0]
        if d._op != "dequantize" or len(d._inputs) != 3:
            continue
        q, mn, mx_ = d._inputs
        if q._op not in _LATTICE_OUT_OPS:
            continue
        if not (_key(q) == _key(mn) == _key(mx_)):
            continue  # ranges come from somewhere else: not a pure pair
        if (q._output_index, mn._output_index, mx_._output_index) != \
                (0, 1, 2):
            continue
        if any(c._op not in _TRIPLE_CONSUMERS
               for c in consumers.get(k, ())):
            continue  # a non-quantized consumer reads this node: keep it
        mapping[k] = q
    graph.apply(mapping)
    _count("islands_elided", len(mapping))
    return len(mapping)


# ---------------------------------------------------------------------------
# pass 3: calibration folding

def _calib_const(node_name, idx, value, const_memo):
    from ..symbol import Symbol

    ck = (node_name, idx)
    sym = const_memo.get(ck)
    if sym is None:
        sym = Symbol(op="_sym_constant",
                     name=f"{node_name}_calib{idx}",
                     kwargs={"value": float(value), "shape": (1,),
                             "dtype": "float32"})
        const_memo[ck] = sym
    return sym


def _quantize_calibrate(graph, ctx):
    """Fold the calibration table into the graph: boundary nodes gain
    ``min/max_calib_range`` kwargs (auto mode upgrades non-negative
    flexible boundaries to uint8), then every statically-known range
    output is replaced by a ``_sym_constant`` scalar in its consumers so
    the scale arithmetic downstream of it constant-folds."""
    scope = current_scope()
    if scope is None:
        return 0
    from ..symbol import Symbol

    mapping = {}
    calibrated = 0
    for n in graph.nodes:
        meta = scope.meta.get(n._name or "")
        if meta is None or n._op not in (
                "quantize_v2", "requantize",
                "_contrib_quantized_batch_norm"):
            continue
        rng = scope.calib_ranges.get(meta["src"])
        if rng is None:
            continue
        kw = dict(n._kwargs)
        kw["min_calib_range"] = float(rng[0])
        kw["max_calib_range"] = float(rng[1])
        if n._op == "quantize_v2" and meta["flex"] and \
                scope.auto_dtype and float(rng[0]) >= 0.0:
            # reference 'auto' mode: provably non-negative (post-relu)
            # boundaries take the uint8 lattice's extra resolution
            kw["out_type"] = "uint8"
            _count("uint8_boundaries")
        rep = Symbol(op=n._op, name=n._name, inputs=list(n._inputs),
                     kwargs=kw, num_outputs=n._num_outputs)
        rep._attrs.update(n._attrs)
        mapping[_key(n)] = rep
        calibrated += 1
    graph.apply(mapping)
    _count("nodes_calibrated", calibrated)

    # every calibrated node's range outputs are now static — re-point
    # consumer references at _sym_constant scalars (the encode rules in
    # ndarray/ops_quant.py: int8 lattices carry (-amax, +amax), uint8
    # carries (0, max))
    static = {}  # producer key -> (min value, max value)
    for n in graph.nodes:
        if n._op not in ("quantize_v2", "requantize",
                         "_contrib_quantized_batch_norm"):
            continue
        kw = n._kwargs
        if kw.get("min_calib_range") is None or \
                kw.get("max_calib_range") is None:
            continue
        cmn = float(kw["min_calib_range"])
        cmx = float(kw["max_calib_range"])
        if n._op == "quantize_v2" and kw.get("out_type") == "uint8":
            static[_key(n)] = (0.0, cmx)
        else:
            amax = max(abs(cmn), abs(cmx))
            static[_key(n)] = (-amax, amax)
    if not static:
        return calibrated

    by_key = {}
    for n in graph.nodes:
        by_key.setdefault(_key(n), n)
    head_keys = {_key(h) for h in graph.heads}
    const_memo = {}
    folded = {}
    for n in graph.nodes:
        if _key(n) in head_keys and n._op is None:
            continue
        new_inputs, changed = [], False
        for i in n._inputs:
            vals = static.get(_key(i))
            if vals is not None and i._output_index in (1, 2):
                prod = by_key[_key(i)]
                new_inputs.append(_calib_const(
                    prod._name or "q", i._output_index,
                    vals[i._output_index - 1], const_memo))
                changed = True
            else:
                new_inputs.append(i)
        if changed:
            rep = Symbol(op=n._op, name=n._name, inputs=new_inputs,
                         kwargs=dict(n._kwargs),
                         num_outputs=n._num_outputs)
            rep._attrs.update(n._attrs)
            folded[_key(n)] = rep
    graph.apply(folded)
    # graph.apply only enlists a replacement's direct nodes — make the
    # shared constants first-class work-list members so cse/dce see them
    for sym in const_memo.values():
        ck = _key(sym)
        if ck not in graph._keys:
            graph._keys.add(ck)
            graph.nodes.insert(0, sym)
    _count("scales_folded", len(const_memo))
    return calibrated + len(folded)


# ---------------------------------------------------------------------------
# registration + serving salt

#: scheduled via optimize_symbol(..., passes=QUANTIZE_PIPELINE) — the
#: quantize rewrites inherit the standard post-verify rejection net, and
#: fold/cse/dce clean up orphaned fp32 islands and duplicate boundaries
QUANTIZE_PIPELINE = ("quantize_insert", "quantize_elide",
                     "quantize_calibrate", "fold", "cse", "dce")


def kv_page_codes(pages):
    """Pure quantization math for :func:`quantize_kv_page` — traceable
    (no counter side effects), so the paged state store can fuse it into
    its jitted scatter kernel. Callers that trace this are responsible
    for bumping ``kv_pages_quantized`` themselves, outside the trace."""
    import jax.numpy as jnp

    red = tuple(range(1, pages.ndim))
    amax = jnp.max(jnp.abs(pages), axis=red)
    scale = amax / 127.0
    denom = jnp.where(scale > 0, scale, 1.0)
    shape = scale.shape + (1,) * (pages.ndim - 1)
    q = jnp.clip(jnp.round(pages / denom.reshape(shape)),
                 -127, 127).astype(jnp.int8)
    return q, scale


def quantize_kv_page(pages):
    """Symmetric per-page int8 quantization for paged KV-cache storage
    (round 21): ``pages`` is a batch-first fp32 block ``(n, ...)``;
    returns ``(int8 codes, fp32 per-page scales (n,))``. The lattice's
    symmetric (-amax, +amax) convention — zero-point-free, so a page of
    zeros round-trips to exact zeros and the attention mask's
    guarantees survive quantization."""
    q, scale = kv_page_codes(pages)
    _count("kv_pages_quantized", int(pages.shape[0]))
    return q, scale


def dequantize_kv_pages(q, scales):
    """Inverse of :func:`quantize_kv_page`, broadcasting per-page
    scales over trailing axes (``q`` may carry extra leading batch
    axes as long as ``scales`` matches them)."""
    import jax.numpy as jnp

    shape = scales.shape + (1,) * (q.ndim - scales.ndim)
    return q.astype(jnp.float32) * scales.reshape(shape)


def fingerprint_salt(graph_signature):
    """Compile-cache salt for graphs that execute quantized-lattice ops:
    their lowering is backend/knob-dependent (MXNET_QUANTIZE_LOWERING —
    native int8 on TPU MXUs, weight-dequant fp32 accumulation where XLA
    has no fast int8 path), so int8 artifacts compiled under different
    lowerings must never collide. fp32 graphs contribute nothing, which
    keeps every pre-existing cache key stable."""
    if "_contrib_quantized_" not in graph_signature:
        return ()
    from ..ndarray.ops_quant import lowering

    return ("quantize", lowering())


def _register():
    from .graph_opt import REWRITE_PASSES, RewritePass

    REWRITE_PASSES["quantize_insert"] = RewritePass(
        "quantize_insert", _quantize_insert,
        "wrap quantizable ops in per-node int8 islands")
    REWRITE_PASSES["quantize_elide"] = RewritePass(
        "quantize_elide", _quantize_elide,
        "merge adjacent int8 islands (dequant->quant pair elision)")
    REWRITE_PASSES["quantize_calibrate"] = RewritePass(
        "quantize_calibrate", _quantize_calibrate,
        "fold calibration statistics into kwargs + constant scales")


_register()


# -- artifact-layer salt provider -------------------------------------------

def _salt_provider(ctx):
    sig = ctx.get("graph_signature")
    return fingerprint_salt(sig) if sig is not None else ()


from ..artifact import salts as _artifact_salts  # noqa: E402

_artifact_salts.register_salt_provider("quantize", _salt_provider)
