"""Device context.

TPU-native analog of mxnet.context.Context (reference:
python/mxnet/context.py, include/mxnet/base.h Context struct). Device types:
``cpu`` and ``tpu`` (``gpu`` is accepted as an alias of ``tpu`` so reference
scripts run unchanged). A Context maps to a concrete ``jax.Device``; NDArrays
are committed to that device with ``jax.device_put``.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus",
           "num_tpus", "gpu_memory_info"]


class Context:
    """Device context holding device type and id.

    Usable as a `with` scope to set the default context, like the reference
    (reference: python/mxnet/context.py:126-132).
    """

    _default_ctx = threading.local()

    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared"}
    devstr2type = {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5}

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # ---- jax mapping ----------------------------------------------------
    @property
    def jax_device(self):
        """The concrete jax.Device backing this context."""
        if self.device_type == "cpu":
            devs = [d for d in jax.devices() if d.platform == "cpu"]
            if not devs:
                devs = jax.devices()
        else:
            devs = [d for d in jax.devices() if d.platform != "cpu"]
            if not devs:  # CPU-only host (tests): tpu(i) falls back to cpu devices
                devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Free cached device memory (reference: Context.empty_cache,
        python/mxnet/context.py:161; GPUPooledStorageManager::ReleaseAll,
        src/storage/pooled_storage_manager.h). XLA/PJRT manages its own pool;
        this triggers a best-effort GC."""
        import gc

        gc.collect()


def cpu(device_id=0):
    return Context("cpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Alias of tpu() so reference scripts using mx.gpu() run on TPU."""
    return Context("tpu", device_id)


def num_tpus():
    return len([d for d in jax.devices() if d.platform != "cpu"])


def num_gpus():
    """Reference: mxnet.context.num_gpus — here the number of TPU chips."""
    return num_tpus()


def gpu_memory_info(device_id=0):
    """(free, total) accelerator memory in bytes (reference:
    context.gpu_memory_info over cudaMemGetInfo; here PJRT's per-device
    HBM accounting via the Storage interface). Raises on an invalid
    device id, matching the reference (and util.get_gpu_memory)."""
    from .util import get_gpu_memory

    return get_gpu_memory(device_id)


def current_context():
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value
