"""Custom Python operators (reference: python/mxnet/operator.py, 1160 LoC:
CustomOp/CustomOpProp + ctypes callbacks into src/operator/custom/custom.cc
which runs them on a dedicated thread pool with kAsync exec).

TPU-native: eager calls run the Python body directly on NDArrays (JAX
async dispatch already gives the reference's async behavior); under jit
tracing the body runs via jax.pure_callback so hybridized graphs can embed
host Python ops. Autograd records one tape node whose backward calls the
user's `backward` (need_top_grad semantics preserved).
"""
from __future__ import annotations

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]

_CUSTOM_OPS = {}


class CustomOp:
    """Base class for user ops (reference: operator.py:CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Reference: CustomOp.assign — honor the grad request."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src


class CustomOpProp:
    """Reference: operator.py:CustomOpProp."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Decorator registering a CustomOpProp under `reg_name`
    (reference: operator.py:register)."""

    def deco(prop_cls):
        _CUSTOM_OPS[reg_name] = prop_cls
        return prop_cls

    return deco


def get_all_registered():
    return dict(_CUSTOM_OPS)


def invoke_custom(op_type, args, kwargs):
    """Execute a registered custom op eagerly (nd.Custom path)."""
    from . import nd, autograd
    from .ndarray import NDArray

    prop_cls = _CUSTOM_OPS.get(op_type)
    if prop_cls is None:
        raise ValueError(f"custom op '{op_type}' not registered")
    str_kwargs = {k: str(v) for k, v in kwargs.items()}
    prop = prop_cls(**str_kwargs)
    n_in = len(prop.list_arguments())
    n_out = len(prop.list_outputs())
    inputs = list(args)
    assert len(inputs) == n_in, \
        f"{op_type} expects {n_in} inputs, got {len(inputs)}"
    in_shapes = [list(a.shape) for a in inputs]
    in_shapes, out_shapes, aux_shapes = prop.infer_shape(in_shapes)
    in_types = [a.dtype for a in inputs]
    _, out_types, _ = prop.infer_type(in_types)
    op = prop.create_operator(None, in_shapes, in_types)

    out_data = [nd.zeros(tuple(s), dtype=t)
                for s, t in zip(out_shapes, out_types)]
    is_train = autograd.is_training()
    # the user body mutates out_data in place (CustomOp.assign); run it
    # untaped — the op's tape node is recorded manually below
    with autograd.pause(train_mode=is_train):
        op.forward(is_train=is_train, req=["write"] * n_out,
                   in_data=inputs, out_data=out_data, aux=[])

    if autograd.is_recording():
        def vjp_fn(cotangents, _op=op, _ins=inputs, _outs=out_data):
            cots = cotangents if isinstance(cotangents, (list, tuple)) \
                else (cotangents,)
            out_grad = [NDArray(c) for c in cots]
            in_grad = [nd.zeros(a.shape, dtype=a.dtype) for a in _ins]
            with autograd.pause():
                _op.backward(req=["write"] * len(_ins), out_grad=out_grad,
                             in_data=_ins, out_data=_outs, in_grad=in_grad,
                             aux=[])
            return tuple(g.data for g in in_grad)

        autograd._record_op(vjp_fn, inputs, out_data)
    return out_data[0] if n_out == 1 else out_data


def _install_nd_custom():
    import sys

    nd_mod = sys.modules.get("mxnet_tpu.ndarray")
    if nd_mod is None:
        return

    def Custom(*args, op_type=None, **kwargs):
        """Reference: autogen Custom op wrapper (custom.cc)."""
        if op_type is None:
            raise ValueError("op_type is required")
        return invoke_custom(op_type, args, kwargs)

    nd_mod.Custom = Custom
