"""Legacy symbolic RNN API (reference: python/mxnet/rnn/__init__.py)."""
from .rnn_cell import (BaseRNNCell, RNNCell, LSTMCell, GRUCell,  # noqa
                       SequentialRNNCell, BidirectionalCell,
                       DropoutCell, FusedRNNCell)
from .io import BucketSentenceIter, encode_sentences  # noqa: F401
