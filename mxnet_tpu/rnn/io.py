"""Bucketed sequence iterators (reference: python/mxnet/rnn/io.py)."""
from __future__ import annotations

import random as pyrandom

import numpy as onp

from .. import ndarray as nd
from ..io.io import DataIter, DataBatch, DataDesc

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0, unknown_token=None):
    """Token sentences -> id sentences, building/extending `vocab`
    (reference io.py:encode_sentences)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    if unknown_token:
                        word = unknown_token
                    else:
                        raise ValueError(f"unknown token {word!r}")
                else:
                    if idx == invalid_label:
                        idx += 1
                    vocab[word] = idx
                    idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Bucketed, padded sentence iterator (reference
    io.py:BucketSentenceIter)."""

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            lens = onp.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size]
        buckets.sort()
        self.buckets = buckets
        self.data = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            buck = onp.searchsorted(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = onp.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        # empty buckets keep a 2-D (0, bucket_len) shape so reset()'s
        # label shift slicing stays valid
        self.data = [onp.asarray(x, dtype=dtype) if x
                     else onp.empty((0, blen), dtype=dtype)
                     for x, blen in zip(self.data, buckets)]
        if ndiscard:
            import logging

            logging.warning("discarded %d sentences longer than the "
                            "largest bucket", ndiscard)
        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.major_axis = layout.find("N")
        self.default_bucket_key = max(buckets)
        # provide_* reflect the LARGEST bucket (reference behavior)
        shape = (batch_size, self.default_bucket_key) \
            if self.major_axis == 0 \
            else (self.default_bucket_key, batch_size)
        self.provide_data = [DataDesc(data_name, shape, layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, layout=layout)]
        self.idx = [(i, j) for i, buck in enumerate(self.data)
                    for j in range(0, len(buck) - batch_size + 1,
                                   batch_size)]
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        pyrandom.shuffle(self.idx)
        for buck in self.data:
            onp.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = onp.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(buck)
            self.ndlabel.append(label)

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        if self.major_axis == 1:
            data = self.nddata[i][j:j + self.batch_size].T
            label = self.ndlabel[i][j:j + self.batch_size].T
        else:
            data = self.nddata[i][j:j + self.batch_size]
            label = self.ndlabel[i][j:j + self.batch_size]
        return DataBatch([nd.array(data)], [nd.array(label)], pad=0,
                         bucket_key=self.buckets[i],
                         provide_data=[DataDesc(
                             self.data_name, data.shape,
                             layout=self.layout)],
                         provide_label=[DataDesc(
                             self.label_name, label.shape,
                             layout=self.layout)])
