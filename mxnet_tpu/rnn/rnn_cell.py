"""Symbolic RNN cells (reference: python/mxnet/rnn/rnn_cell.py).

These build Symbol graphs directly (the pre-Gluon API the reference
keeps for Module/bucketing users); the gluon cells in
mxnet_tpu/gluon/rnn are the eager/hybrid counterparts. Unrolled graphs
lower through the symbolic executor to one jitted XLA computation —
explicit unrolling is XLA-friendly for the short fixed buckets this API
is used with.
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
           "FusedRNNCell"]


class BaseRNNCell:
    """Reference: rnn_cell.py:BaseRNNCell."""

    def __init__(self, prefix="", params=None):
        self._prefix = prefix
        self._counter = -1
        self._init_counter = -1
        self._modified = False

    @property
    def state_info(self):
        raise NotImplementedError

    def state_row_shapes(self):
        """Per-state PER-ROW shapes (batch axis dropped) — what a
        serving :class:`~mxnet_tpu.serving.state.SessionStateStore`
        needs as its ``state_shapes``: the symbolic ``state_info``
        shapes lead with the 0 batch placeholder."""
        return [tuple(info["shape"][1:]) for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def reset(self):
        self._counter = -1
        self._init_counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    def _var(self, name):
        return sym.Variable(self._prefix + name)

    def begin_state(self, func=None, **kwargs):
        """Initial-state symbols (reference rnn_cell.py begin_state)."""
        states = []
        for info in self.state_info:
            self._init_counter += 1
            states.append(sym.Variable(
                f"{self._prefix}begin_state_{self._init_counter}"))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll over `length` steps (reference rnn_cell.py:unroll).

        inputs: one Symbol (N,T,C) split on the time axis, or a list of
        per-step Symbols. Returns (outputs, final_states)."""
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, sym.Symbol):
            inputs = list(sym.split(inputs, num_outputs=length,
                                    axis=axis, squeeze_axis=True))
        assert len(inputs) == length
        states = begin_state if begin_state is not None \
            else self.begin_state()
        outputs = []
        for t in range(length):
            out, states = self(inputs[t], states)
            outputs.append(out)
        if merge_outputs:
            outputs = sym.stack(*outputs, axis=axis)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Elman RNN cell (reference rnn_cell.py:RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self._var("i2h_weight")
        self._iB = self._var("i2h_bias")
        self._hW = self._var("h2h_weight")
        self._hB = self._var("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden,
                                 name=name + "i2h")
        h2h = sym.FullyConnected(states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=self._num_hidden,
                                 name=name + "h2h")
        output = sym.Activation(i2h + h2h, act_type=self._activation,
                                name=name + "out")
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference rnn_cell.py:LSTMCell; gate order i,f,c,o).

    ``forget_bias`` is an INITIALIZATION hint, exposed as
    ``bias_init_value()``: the reference seeds the forget-gate slice of
    h2h_bias with it via the LSTMBias initializer; in this symbolic API
    the caller owns parameter values at bind time, so seed your
    h2h_bias with ``bias_init_value()`` to reproduce that behavior (the
    gate math itself is identical either way)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._forget_bias = forget_bias
        self._iW = self._var("i2h_weight")
        self._iB = self._var("i2h_bias")
        self._hW = self._var("h2h_weight")
        self._hB = self._var("h2h_bias")

    def bias_init_value(self):
        """h2h_bias seed honoring forget_bias (reference LSTMBias
        initializer, python/mxnet/initializer.py:LSTMBias)."""
        import numpy as onp

        b = onp.zeros(4 * self._num_hidden, "float32")
        b[self._num_hidden:2 * self._num_hidden] = self._forget_bias
        return b

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        nh = self._num_hidden
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=nh * 4, name=name + "i2h")
        h2h = sym.FullyConnected(states[0], weight=self._hW,
                                 bias=self._hB, num_hidden=nh * 4,
                                 name=name + "h2h")
        gates = i2h + h2h
        sl = list(sym.split(gates, num_outputs=4, axis=-1))
        in_gate = sym.Activation(sl[0], act_type="sigmoid")
        forget_gate = sym.Activation(sl[1], act_type="sigmoid")
        in_trans = sym.Activation(sl[2], act_type="tanh")
        out_gate = sym.Activation(sl[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * sym.Activation(next_c, act_type="tanh",
                                           name=name + "state")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference rnn_cell.py:GRUCell; gate order r,z,n)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._iW = self._var("i2h_weight")
        self._iB = self._var("i2h_bias")
        self._hW = self._var("h2h_weight")
        self._hB = self._var("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        nh = self._num_hidden
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=nh * 3, name=name + "i2h")
        h2h = sym.FullyConnected(states[0], weight=self._hW,
                                 bias=self._hB, num_hidden=nh * 3,
                                 name=name + "h2h")
        i_r, i_z, i_n = list(sym.split(i2h, num_outputs=3, axis=-1))
        h_r, h_z, h_n = list(sym.split(h2h, num_outputs=3, axis=-1))
        reset = sym.Activation(i_r + h_r, act_type="sigmoid")
        update = sym.Activation(i_z + h_z, act_type="sigmoid")
        trans = sym.Activation(i_n + reset * h_n, act_type="tanh")
        next_h = update * states[0] + (1.0 - update) * trans
        return next_h, [next_h]


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells (reference rnn_cell.py:SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__("", params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def __call__(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            inputs, st = cell(inputs, states[p:p + n])
            next_states.extend(st)
            p += n
        return inputs, next_states

    def reset(self):
        super().reset()
        for c in self._cells:
            c.reset()


class DropoutCell(BaseRNNCell):
    """Reference: rnn_cell.py:DropoutCell."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def begin_state(self, **kwargs):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = sym.Dropout(inputs, p=self.dropout)
        return inputs, states


class BidirectionalCell(BaseRNNCell):
    """Reference: rnn_cell.py:BidirectionalCell — unroll-only."""

    def __init__(self, l_cell, r_cell, params=None,
                 output_prefix="bi_"):
        super().__init__("", params)
        self._l_cell = l_cell
        self._r_cell = r_cell
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return self._l_cell.state_info + self._r_cell.state_info

    def begin_state(self, **kwargs):
        return (self._l_cell.begin_state(**kwargs) +
                self._r_cell.begin_state(**kwargs))

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell cannot be stepped; use unroll "
            "(reference rnn_cell.py:1186)")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, sym.Symbol):
            inputs = list(sym.split(inputs, num_outputs=length,
                                    axis=axis, squeeze_axis=True))
        states = begin_state if begin_state is not None \
            else self.begin_state()
        nl = len(self._l_cell.state_info)
        l_out, l_states = self._l_cell.unroll(
            length, inputs, states[:nl], layout, merge_outputs=False)
        r_out, r_states = self._r_cell.unroll(
            length, list(reversed(inputs)), states[nl:], layout,
            merge_outputs=False)
        outputs = [sym.concat(lo, ro, dim=-1,
                              name=f"{self._output_prefix}t{t}")
                   for t, (lo, ro) in enumerate(
                       zip(l_out, reversed(r_out)))]
        if merge_outputs:
            outputs = sym.stack(*outputs, axis=axis)
        return outputs, l_states + r_states


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN riding the `rnn` op (reference
    rnn_cell.py:FusedRNNCell — cuDNN there, the lax.scan-fused kernel
    here). unfuse() yields the equivalent stacked cells."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, prefix="rnn_",
                 params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout

    @property
    def state_info(self):
        d = 2 if self._bidirectional else 1
        info = [{"shape": (self._num_layers * d, 0, self._num_hidden)}]
        if self._mode == "lstm":
            info.append(
                {"shape": (self._num_layers * d, 0, self._num_hidden)})
        return info

    def unfuse(self):
        cells = SequentialRNNCell()
        ctor = {"rnn_tanh": lambda p: RNNCell(
                    self._num_hidden, "tanh", prefix=p),
                "rnn_relu": lambda p: RNNCell(
                    self._num_hidden, "relu", prefix=p),
                "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
                "gru": lambda p: GRUCell(self._num_hidden, prefix=p)}[
            self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                cells.add(BidirectionalCell(
                    ctor(f"{self._prefix}l{i}_"),
                    ctor(f"{self._prefix}r{i}_"),
                    output_prefix=f"{self._prefix}bi_l{i}_"))
            else:
                cells.add(ctor(f"{self._prefix}l{i}_"))
            if self._dropout > 0 and i != self._num_layers - 1:
                cells.add(DropoutCell(self._dropout,
                                      prefix=f"{self._prefix}_dropout{i}_"))
        return cells

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        return self.unfuse().unroll(length, inputs, begin_state, layout,
                                    merge_outputs)
