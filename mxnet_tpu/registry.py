"""Generic class-factory registry (reference: python/mxnet/registry.py).

`get_register_func` / `get_alias_func` / `get_create_func` build the
register/alias/create triple for a base class, with the reference's
config-string forms: a plain name, a '["name", {kwargs}]' json list, or
a '{"nickname": ..., kwargs}' json dict.
"""
from __future__ import annotations

import json
import warnings

_REGISTRY = {}

__all__ = ["get_registry", "get_register_func", "get_alias_func",
           "get_create_func"]


def _reg_for(base_class):
    return _REGISTRY.setdefault(base_class, {})


def get_registry(base_class):
    """A copy of the name→class mapping registered under base_class."""
    return dict(_reg_for(base_class))


def get_register_func(base_class, nickname):
    registry = _reg_for(base_class)

    def register(klass, name=None):
        assert issubclass(klass, base_class), \
            f"can only register subclasses of {base_class.__name__}"
        name = (name or klass.__name__).lower()
        if name in registry and registry[name] is not klass:
            warnings.warn(
                f"new {nickname} {klass.__module__}.{klass.__name__} "
                f"registered with name {name} overrides existing "
                f"{registry[name].__module__}.{registry[name].__name__}",
                UserWarning, stacklevel=2)
        registry[name] = klass
        return klass

    register.__doc__ = f"Register a {nickname} to the {nickname} factory"
    return register


def get_alias_func(base_class, nickname):
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass

        return reg

    return alias


def get_create_func(base_class, nickname):
    registry = _reg_for(base_class)

    def create(*args, **kwargs):
        if args:
            name, args = args[0], args[1:]
        else:
            name = kwargs.pop(nickname)
        if isinstance(name, base_class):
            assert not args and not kwargs, \
                f"{nickname} is already an instance"
            return name
        if isinstance(name, dict):
            return create(**name)
        assert isinstance(name, str), f"{nickname} must be a string"
        if name.startswith("["):
            assert not args and not kwargs
            name, kwargs = json.loads(name)
            return create(name, **kwargs)
        if name.startswith("{"):
            assert not args and not kwargs
            return create(**json.loads(name))
        name = name.lower()
        assert name in registry, \
            f"{name} is not registered; register with {nickname}.register"
        return registry[name](*args, **kwargs)

    create.__doc__ = f"Create a {nickname} instance from config"
    return create
