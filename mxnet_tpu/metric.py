"""Streaming evaluation metrics.

TPU-native equivalent of python/mxnet/metric.py (reference: registry +
EvalMetric; Accuracy/TopK/F1/MCC/Perplexity/MAE/MSE/RMSE/CrossEntropy/
NegativeLogLikelihood/PearsonCorrelation/Loss/CustomMetric/Composite).
Metric math is numpy on host — the device only ships predictions out once
per batch, matching the reference's update-on-CPU behavior.
"""
from __future__ import annotations

import math

import numpy as onp

from .base import register_entry, lookup_entry

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register", "check_label_shapes"]


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(f"Shape of labels {label_shape} does not match "
                         f"shape of predictions {pred_shape}")
    if wrap:
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
    return labels, preds


def _to_numpy(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


def register(klass):
    register_entry("metric", klass.__name__, klass, override=True)
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    try:  # exact registry name first (custom registered metrics)
        return lookup_entry("metric", metric)(*args, **kwargs)
    except ValueError:
        pass
    aliases = {"acc": "accuracy", "ce": "crossentropy",
               "nll_loss": "negativeloglikelihood",
               "top_k_accuracy": "topkaccuracy", "top_k_acc": "topkaccuracy",
               "pearsonr": "pearsoncorrelation",
               "composite": "compositeevalmetric"}
    key = aliases.get(metric.lower(),
                      metric.lower().replace("-", "").replace("_", ""))
    return lookup_entry("metric", key)(*args, **kwargs)


class EvalMetric:
    """Base streaming metric (reference: metric.py EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(zip(*self.get()))}"

    def get_config(self):
        config = dict(self._kwargs)
        config.update({"metric": type(self).__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = metrics if metrics is not None else []

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.extend(name if isinstance(name, list) else [name])
            values.extend(value if isinstance(value, list) else [value])
        return names, values


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label, pred = _to_numpy(label), _to_numpy(pred)
            if pred.ndim > label.ndim:
                pred = onp.argmax(pred, axis=self.axis)
            pred = pred.astype("int32").ravel()
            label = label.astype("int32").ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label, pred = _to_numpy(label), _to_numpy(pred)
            pred_idx = onp.argsort(-pred, axis=1)[:, :self.top_k]
            label = label.astype("int32")
            self.sum_metric += (pred_idx == label[:, None]).any(axis=1).sum()
            self.num_inst += len(label)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        super().__init__(name, output_names, label_names)

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _to_numpy(label).ravel(), _to_numpy(pred)
            pred = (pred[:, 1] > 0.5).astype("int32") if pred.ndim == 2 \
                else (pred > 0.5).astype("int32")
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            prec = self._tp / max(self._tp + self._fp, 1e-12)
            rec = self._tp / max(self._tp + self._fn, 1e-12)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MCC(EvalMetric):
    def __init__(self, name="mcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = self._tn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _to_numpy(label).ravel(), _to_numpy(pred)
            pred = (pred[:, 1] > 0.5).astype("int32") if pred.ndim == 2 \
                else (pred > 0.5).astype("int32")
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            self._tn += ((pred == 0) & (label == 0)).sum()
            denom = math.sqrt((self._tp + self._fp) * (self._tp + self._fn)
                              * (self._tn + self._fp) * (self._tn + self._fn))
            mcc = (self._tp * self._tn - self._fp * self._fn) / max(denom, 1e-12)
            self.sum_metric = mcc
            self.num_inst = 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label, pred = _to_numpy(label), _to_numpy(pred)
            label = label.astype("int32").ravel()
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[onp.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = onp.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= onp.sum(onp.log(onp.maximum(1e-10, probs)))
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label, pred = _to_numpy(label), _to_numpy(pred)
            if label.ndim == 1 and pred.ndim == 2 and pred.shape[1] == 1:
                pred = pred.ravel()
            self.sum_metric += onp.abs(label - pred).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label, pred = _to_numpy(label), _to_numpy(pred)
            if label.ndim == 1 and pred.ndim == 2 and pred.shape[1] == 1:
                pred = pred.ravel()
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label, pred = _to_numpy(label).ravel(), _to_numpy(pred)
            probs = pred[onp.arange(label.shape[0]), label.astype("int64")]
            self.sum_metric += (-onp.log(probs + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps=eps, name=name, output_names=output_names,
                         label_names=label_names)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label, pred = _to_numpy(label).ravel(), _to_numpy(pred).ravel()
            self.sum_metric += onp.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if isinstance(preds, list):
            for pred in preds:
                loss = _to_numpy(pred)
                self.sum_metric += loss.sum()
                self.num_inst += loss.size
        else:
            loss = _to_numpy(preds)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = getattr(feval, "__name__", "custom")
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            label, pred = _to_numpy(label), _to_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval as a metric (reference: metric.py np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = name if name else getattr(numpy_feval, "__name__", "custom")
    return CustomMetric(feval, name, allow_extra_outputs)
