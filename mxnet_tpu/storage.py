"""Pooled host storage manager (reference: include/mxnet/storage.h +
src/storage/pooled_storage_manager.h — exact-size bucket recycling with
env-tunable behavior).

On TPU, HBM is owned by PJRT/XLA (the north star's device allocator);
this native pool (native/engine.cc:PooledStorage) manages HOST buffers —
IO batch staging, recordio chunks, shm-style transfer buffers — where the
reference used its CPU/pinned managers. `MXNET_CPU_MEM_POOL_DISABLE=1`
falls back to plain malloc-per-alloc semantics (pool bypass).
"""
from __future__ import annotations

import ctypes
import os

import numpy as onp

__all__ = ["Storage", "get", "device_memory_info"]


class _Handle:
    __slots__ = ("ptr", "size")

    def __init__(self, ptr, size):
        self.ptr = ptr
        self.size = size


class Storage:
    def __init__(self):
        from . import _native

        self._lib = None
        if _native.englib is not None:
            L = _native.englib
            L.pool_create.restype = ctypes.c_void_p
            has_create2 = hasattr(L, "pool_create2")
            if has_create2:  # stale prebuilt .so may predate strategies
                L.pool_create2.restype = ctypes.c_void_p
                L.pool_create2.argtypes = [ctypes.c_int, ctypes.c_int64]
            L.pool_alloc.restype = ctypes.c_void_p
            L.pool_alloc.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            L.pool_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            L.pool_direct_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            L.pool_release_all.argtypes = [ctypes.c_void_p]
            L.pool_destroy.argtypes = [ctypes.c_void_p]
            L.pool_stats.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_int64)]
            self._lib = L
            # strategy + reserve knobs (reference MXNET_GPU_MEM_POOL_TYPE
            # / _RESERVE steer the GPU pool; on TPU HBM belongs to PJRT,
            # so they steer this host pool — Round = pow2 buckets,
            # Naive = exact-size, Unpooled = plain malloc/free)
            from . import env as _env

            strategy = {"Naive": 0, "Round": 1, "Unpooled": 2}.get(
                _env.get_str("MXNET_GPU_MEM_POOL_TYPE", "Naive"), 0)
            reserve = _env.get_int("MXNET_GPU_MEM_POOL_RESERVE", 0)
            cap = -1
            if reserve > 0:
                try:  # keep at most (100-reserve)% of phys mem pooled
                    page = os.sysconf("SC_PAGE_SIZE")
                    phys = os.sysconf("SC_PHYS_PAGES") * page
                    cap = phys * max(0, 100 - reserve) // 100
                except (ValueError, OSError):
                    cap = -1
            self._h = (L.pool_create2(strategy, cap) if has_create2
                       else L.pool_create())
        self._fallback = {}

    @property
    def native(self):
        return self._lib is not None

    def alloc(self, size):
        """→ handle with .ptr/.size (reference: Storage::Alloc)."""
        from . import env as _env

        if self._lib is not None and not _env.get_bool(
                "MXNET_CPU_MEM_POOL_DISABLE"):
            ptr = self._lib.pool_alloc(self._h, int(size))
            if ptr:
                return _Handle(ptr, size)
        buf = ctypes.create_string_buffer(int(size))
        h = _Handle(ctypes.addressof(buf), size)
        self._fallback[h.ptr] = buf
        return h

    def free(self, handle):
        """Return to the pool (reference: Storage::Free)."""
        if handle.ptr in self._fallback:
            del self._fallback[handle.ptr]
            return
        if self._lib is not None:
            self._lib.pool_free(self._h, handle.ptr)

    def direct_free(self, handle):
        if handle.ptr in self._fallback:
            del self._fallback[handle.ptr]
            return
        if self._lib is not None:
            self._lib.pool_direct_free(self._h, handle.ptr)

    def release_all(self):
        if self._lib is not None:
            self._lib.pool_release_all(self._h)

    def stats(self):
        """→ dict(used_bytes, pooled_bytes, total_mallocs)."""
        if self._lib is None:
            used = sum(len(b) for b in self._fallback.values())
            return {"used_bytes": used, "pooled_bytes": 0,
                    "total_mallocs": len(self._fallback)}
        out = (ctypes.c_int64 * 3)()
        self._lib.pool_stats(self._h, out)
        return {"used_bytes": int(out[0]), "pooled_bytes": int(out[1]),
                "total_mallocs": int(out[2])}

    def as_array(self, handle, shape, dtype=onp.uint8):
        """Zero-copy numpy view of a pooled buffer (IO staging)."""
        n = int(onp.prod(shape)) * onp.dtype(dtype).itemsize
        assert n <= handle.size, (n, handle.size)
        buf = (ctypes.c_ubyte * handle.size).from_address(handle.ptr)
        return onp.frombuffer(buf, dtype=dtype,
                              count=int(onp.prod(shape))).reshape(shape)


_storage = None


def device_memory_info(ctx=None):
    """(free, total, stats) for an accelerator's HBM through the Storage
    interface (reference: Storage::Get()->... / cudaMemGetInfo behind
    mx.context.gpu_memory_info). The pool itself is PJRT's — this fronts
    its per-device accounting: bytes_in_use, peak_bytes_in_use,
    bytes_limit and friends from the PJRT allocator."""
    import jax

    if ctx is None:
        devs = [d for d in jax.devices() if d.platform != "cpu"]
        dev = devs[0] if devs else jax.devices()[0]
    else:
        dev = getattr(ctx, "jax_device", ctx)
    stats = dict(dev.memory_stats() or {})
    total = stats.get("bytes_limit", 0)
    used = stats.get("bytes_in_use", 0)
    free = max(total - used, 0) if total else 0
    return free, total, stats


def get():
    """Singleton (reference: Storage::Get())."""
    global _storage
    if _storage is None:
        _storage = Storage()
    return _storage
