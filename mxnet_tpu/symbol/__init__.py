"""Symbolic API (mx.sym).

TPU-native redesign of the reference's NNVM Symbol layer (reference:
python/mxnet/symbol/symbol.py 3359 LoC over 3rdparty/tvm/nnvm Symbol/Graph;
src/executor/graph_executor.cc). A Symbol here is a lightweight DAG of op
nodes over the SAME op registry that powers mx.nd — binding lowers the
whole graph to one jitted XLA computation (the analog of GraphExecutor's
bind: memory planning, fusion and scheduling delegated to XLA instead of
MXPlanMemory/engine bulking). JSON save/load keeps Module checkpoint
compatibility at the API level.
"""
from __future__ import annotations

import json
import sys as _sys

import numpy as onp

from ..base import MXNetError
from ..ndarray import registry as _registry

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones"]


class Symbol:
    """A node (or group of output nodes) in a symbolic graph."""

    def __init__(self, op=None, name=None, inputs=None, kwargs=None,
                 num_outputs=1, output_index=0, group=None):
        self._op = op  # str op name; None for variables/groups
        self._name = name
        # `is not None` (not truthiness): __getitem__ views must share
        # the SAME list/dict objects as their base even when empty —
        # node identity keys are (op, id(_inputs), id(_kwargs)), and an
        # `or {}` here would give every view of an empty-kwargs
        # multi-output node a fresh dict, i.e. a fresh identity
        self._inputs = inputs if inputs is not None else []  # list[Symbol]
        self._kwargs = kwargs if kwargs is not None else {}
        self._num_outputs = num_outputs
        self._output_index = output_index
        self._group = group  # list[Symbol] when this is a Group
        self._attrs = {}

    # ---- construction ----------------------------------------------------
    @property
    def name(self):
        return self._name

    def attr(self, key):
        return self._attrs.get(key)

    def _set_attr(self, **kwargs):
        self._attrs.update({k: str(v) for k, v in kwargs.items()})

    def list_attr(self):
        return dict(self._attrs)

    def __repr__(self):
        return f"<Symbol {self._name or self._op}>"

    def __copy__(self):
        return self

    # ---- graph queries ---------------------------------------------------
    def _walk(self, seen=None, order=None):
        if seen is None:
            seen, order = set(), []
        if id(self) in seen:
            return order
        seen.add(id(self))
        for i in self._inputs:
            i._walk(seen, order)
        if self._group:
            for g in self._group:
                g._walk(seen, order)
        order.append(self)
        return order

    def list_arguments(self):
        """Free variables in topological order (reference:
        symbol.py list_arguments). Auxiliary states (variables tagged
        __aux__, e.g. BN running stats) are excluded — they are not
        optimizer-visible arguments."""
        return [s._name for s in self._walk()
                if s._op is None and s._group is None
                and "__aux__" not in s._attrs]

    def list_outputs(self):
        if self._group:
            return [n for g in self._group for n in g.list_outputs()]
        base = self._name or self._op
        if self._num_outputs == 1:
            return [f"{base}_output"]
        return [f"{base}_output{i}" for i in range(self._num_outputs)]

    def list_auxiliary_states(self):
        """Mutable non-gradient states (reference: symbol.py
        list_auxiliary_states — BN moving_mean/moving_var et al.)."""
        return [s._name for s in self._walk()
                if s._op is None and s._group is None
                and "__aux__" in s._attrs]

    def get_internals(self):
        return Group([s for s in self._walk() if s._op is not None] or [self])

    def __getitem__(self, index):
        if self._group:
            return self._group[index]
        if isinstance(index, str):
            for s in self._walk():
                if (s._name or s._op) and index.startswith(s._name or ""):
                    if index in s.list_outputs() or index == s._name:
                        return s
            raise ValueError(f"no output named {index}")
        if index < 0 or index >= self._num_outputs:
            # terminate the sequence protocol so `U, L = sym.op(...)`
            # unpacking works on multi-output nodes
            raise IndexError(
                f"output index {index} out of range "
                f"({self._num_outputs} outputs)")
        if self._num_outputs == 1 and index == 0:
            return self
        return Symbol(op=self._op, name=self._name, inputs=self._inputs,
                      kwargs=self._kwargs, num_outputs=self._num_outputs,
                      output_index=index)

    # ---- evaluation ------------------------------------------------------
    def _eval_nodes(self, feed, cache):
        """Topologically evaluate; feed maps var name → NDArray."""
        from .. import ndarray as nd
        from ..ndarray import NDArray

        # output views made by __getitem__ share the base node's _inputs
        # and _kwargs objects — keying op nodes on those identities makes
        # every view hit ONE evaluation of the underlying multi-output op
        # instead of re-invoking it per view
        key = (self._op, id(self._inputs), id(self._kwargs)) \
            if self._op is not None else id(self)
        if key in cache:
            out = cache[key]
            if self._op is not None and isinstance(out, (list, tuple)):
                return out[self._output_index] \
                    if self._num_outputs > 1 else out
            return out
        if self._group is not None:
            outs = []
            for g in self._group:
                o = g._eval_nodes(feed, cache)
                outs.extend(o if isinstance(o, (list, tuple)) else [o])
            cache[key] = outs
            return outs
        if self._op is None:
            if self._name not in feed:
                raise MXNetError(f"variable '{self._name}' is not bound")
            cache[key] = feed[self._name]
            return cache[key]
        args = []
        for i in self._inputs:
            v = i._eval_nodes(feed, cache)
            if isinstance(v, (list, tuple)):
                v = v[i._output_index]
            args.append(v)
        opdef = _registry.get_op(self._op)
        if opdef is None:
            raise MXNetError(f"op '{self._op}' is not registered")
        kwargs = dict(self._kwargs)
        out = _registry.invoke(opdef, tuple(args), kwargs)
        cache[key] = out
        if isinstance(out, (list, tuple)):
            return out[self._output_index] if self._num_outputs > 1 else out
        return out

    def eval_with(self, feed):
        out = self._eval_nodes(dict(feed), {})
        if isinstance(out, (list, tuple)) and self._num_outputs > 1:
            return out[self._output_index]
        return out

    def eval(self, ctx=None, **kwargs):
        """Reference: symbol.py eval."""
        out = self.eval_with(kwargs)
        return out if isinstance(out, list) else [out]

    # ---- shape/type inference -------------------------------------------
    def infer_shape(self, **kwargs):
        """Reference: symbol.py infer_shape — partial inference: parameter
        shapes are derived from layer semantics (symbol/infer.py), output
        shapes from jax.eval_shape over each op body."""
        from .infer import infer_shapes

        known = {k: tuple(v) for k, v in kwargs.items()}
        var_shapes, out_shapes = infer_shapes(self, known)
        args = self.list_arguments()
        aux = self.list_auxiliary_states()
        return ([var_shapes.get(a) for a in args], out_shapes,
                [var_shapes.get(a) for a in aux])

    def infer_shape_partial(self, **kwargs):
        from .infer import infer_shapes

        known = {k: tuple(v) for k, v in kwargs.items()}
        var_shapes, out_shapes = infer_shapes(self, known,
                                              allow_unknown=True)
        args = self.list_arguments()
        return ([var_shapes.get(a) for a in args], out_shapes, [])

    def infer_type(self, **kwargs):
        """Reference: symbol.py infer_type — forward FInferType pass
        (symbol/infer.py infer_types); unknown arguments default to
        float32 like the reference's executor bind."""
        from .infer import infer_types

        known = {k: onp.dtype(v) for k, v in kwargs.items()}
        var_types, out_types = infer_types(self, known)
        args = self.list_arguments()
        aux = self.list_auxiliary_states()
        return ([var_types.get(a, onp.dtype(onp.float32)) for a in args],
                out_types,
                [var_types.get(a, onp.dtype(onp.float32)) for a in aux])

    # ---- binding ---------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", **kwargs):
        """Reference: MXExecutorSimpleBindEx (c_api_executor.cc:189) →
        GraphExecutor::Init. Allocates arg/grad arrays from shapes and
        returns a jit-compiled Executor."""
        from .. import ndarray as nd
        from ..executor import Executor

        arg_shapes, out_shapes, aux_shapes = self.infer_shape(**kwargs)
        args = self.list_arguments()
        aux = self.list_auxiliary_states()
        missing = [a for a, s in zip(args, arg_shapes) if s is None] + \
            [a for a, s in zip(aux, aux_shapes) if s is None]
        if missing:
            raise MXNetError(f"simple_bind could not infer shapes for "
                             f"{missing}")
        arg_arrays = [nd.zeros(s) for s in arg_shapes]
        grad_arrays = [nd.zeros(s) for s in arg_shapes] \
            if grad_req != "null" else None
        aux_arrays = [_default_aux_array(n, s)
                      for n, s in zip(aux, aux_shapes)]
        return Executor(self, args, arg_arrays, grad_arrays, grad_req, ctx,
                        aux_names=aux, aux_arrays=aux_arrays,
                        output_shapes=out_shapes)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        """Reference: executor.h:143 Bind."""
        from ..executor import Executor

        names = self.list_arguments()
        if isinstance(args, dict):
            arg_arrays = [args[n] for n in names]
        else:
            arg_arrays = list(args)
        if args_grad is None:
            grad_arrays = None
        elif isinstance(args_grad, dict):
            grad_arrays = [args_grad.get(n) for n in names]
        else:
            grad_arrays = list(args_grad)
        aux = self.list_auxiliary_states()
        from .. import ndarray as _ndmod

        if isinstance(aux_states, dict):
            aux_arrays = [aux_states[n] for n in aux]
        elif aux_states is not None:
            aux_arrays = list(aux_states)
        elif aux:
            _, _, aux_shapes = self.infer_shape(
                **{n: tuple(a.shape) for n, a in zip(names, arg_arrays)})
            missing = [n for n, sh in zip(aux, aux_shapes) if sh is None]
            if missing:  # fail HERE, not deep inside the first forward
                raise MXNetError(
                    f"bind could not infer aux-state shapes for {missing}; "
                    "pass aux_states explicitly")
            aux_arrays = [_default_aux_array(n, sh)
                          for n, sh in zip(aux, aux_shapes)]
        else:
            aux_arrays = []
        return Executor(self, names, arg_arrays, grad_arrays, grad_req, ctx,
                        aux_names=aux, aux_arrays=aux_arrays)

    # ---- serialization ---------------------------------------------------
    def tojson(self):
        """Emit reference-format nnvm graph JSON (reference: symbol.py
        tojson → nnvm/src/core/graph.cc JSON; format spec observed in
        reference model-zoo ``*-symbol.json`` files): CamelCase legacy op
        names where they exist, all attr values stringified MXNet-style
        ("(3, 3)", "True"), node_row_ptr, and a version stamp. Loadable
        by both `symbol.load` here and reference-era tooling."""
        from ..ndarray import _CAMEL_ALIASES

        # SoftmaxActivation is a LOSSY alias (different op/params in the
        # reference) — never reverse-map onto it. Later table entries are
        # LEGACY-ONLY aliases (BatchNorm_v1, _contrib_quantize_v2, ...):
        # they must load but never win the reverse mapping, so the FIRST
        # alias per target (the canonical CamelCase name) is kept.
        rev = {}
        for k, v in _CAMEL_ALIASES.items():
            if k != "SoftmaxActivation":
                rev.setdefault(v, k)
        # canonicalize: output-view Symbols (same node, different
        # output_index) must collapse to ONE emitted node, keyed by name
        order, idx = [], {}
        for s in self._walk():
            if s._group:  # Group wrapper is not a graph node
                continue
            key = s._name
            if key not in idx:
                idx[key] = len(order)
                order.append(s)

        def attr_str(v):
            if isinstance(v, bool):
                return "True" if v else "False"
            if isinstance(v, (list, tuple)):
                return "(" + ", ".join(str(x) for x in v) + ")"
            return str(v)

        nodes = []
        row_ptr = [0]
        for s in order:
            node = {
                "op": "null" if s._op is None else rev.get(s._op, s._op),
                "name": s._name or (s._op + str(idx[s._name])),
                "inputs": [[idx[i._name], i._output_index, 0]
                           for i in s._inputs],
            }
            merged = {}
            if s._op is not None and s._kwargs:
                merged.update({k: attr_str(v)
                               for k, v in s._kwargs.items()})
            # user attrs (AttrScope stamps, __lr_mult__, ctx_group,
            # __shape__/__aux__ on variables) ride in the same "attrs"
            # dict, like reference nnvm JSON
            merged.update({k: attr_str(v) for k, v in s._attrs.items()})
            if merged:
                node["attrs"] = merged
            nodes.append(node)
            row_ptr.append(row_ptr[-1] + s._num_outputs)
        heads = ([[idx[g._name], g._output_index, 0] for g in self._group]
                 if self._group else [[idx[self._name],
                                       self._output_index, 0]])
        return json.dumps({
            "nodes": nodes,
            "arg_nodes": [i for i, s in enumerate(order) if s._op is None],
            "node_row_ptr": row_ptr,
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 10500]}}, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ---- operators -------------------------------------------------------
    def _binop(self, opname, other, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _make_node(opname, [a, b], {})
        return _make_node(opname + "_scalar", [self],
                          {"scalar": other, "reverse": reverse})

    def __add__(self, o): return self._binop("broadcast_add", o)
    def __radd__(self, o): return self._binop("broadcast_add", o, True)
    def __sub__(self, o): return self._binop("broadcast_sub", o)
    def __rsub__(self, o): return self._binop("broadcast_sub", o, True)
    def __mul__(self, o): return self._binop("broadcast_mul", o)
    def __rmul__(self, o): return self._binop("broadcast_mul", o, True)
    def __truediv__(self, o): return self._binop("broadcast_div", o)
    def __rtruediv__(self, o): return self._binop("broadcast_div", o, True)
    def __pow__(self, o): return self._binop("broadcast_power", o)
    def __neg__(self): return _make_node("negative", [self], {})

    def reshape(self, shape):
        return _make_node("reshape", [self], {"shape": shape})

    def transpose(self, axes=None):
        return _make_node("transpose", [self], {"axes": axes})




def Variable(name=None, shape=None, dtype=None, init=None, **kwargs):
    """Reference: symbol.py Variable/var."""
    from .. import attribute, name as _name_mod

    if name is None:
        # explicit variable names are used verbatim (reference var()
        # never consults NameManager); only auto-names go through it
        name = _name_mod.current().get(None, "var")
    s = Symbol(op=None, name=name)
    scope_attrs = attribute.current().get(kwargs.pop("attr", None))
    if scope_attrs:
        s._attrs.update({k: str(v) for k, v in scope_attrs.items()})
    if shape is not None:
        s._attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        # declared dtype rides as an attr like __shape__ (reference JSON
        # stores __dtype__ as a type index; a name is clearer and our
        # loader keeps unknown dunder attrs verbatim) — the analysis
        # layer cross-checks it against inferred/bound dtypes (GV102)
        s._attrs["__dtype__"] = str(onp.dtype(dtype))
    return s


var = Variable


def Group(symbols):
    """Reference: symbol.py Group."""
    return Symbol(group=list(symbols), name="group")




def _num_outputs_for(opname, kwargs):
    """Static output count of a node (reference: each op's num_outputs
    attr in the NNVM registry)."""
    if opname in ("split", "split_v2", "slice_channel"):
        n = kwargs.get("num_outputs")
        if n is None and opname == "split_v2":
            ios = kwargs.get("indices_or_sections")
            n = ios if isinstance(ios, int) else len(ios) + 1
        return int(n or 1)
    if opname == "topk":
        return 2 if kwargs.get("ret_typ") == "both" else 1
    if opname in ("batch_norm", "layer_norm"):
        return 3 if kwargs.get("output_mean_var") else 1
    if opname == "rnn":
        if kwargs.get("state_outputs", True):
            return 3 if kwargs.get("mode", "lstm") == "lstm" else 2
        return 1
    if opname == "histogram":
        return 2
    if opname in ("linalg_gelqf", "linalg_syevd", "linalg_slogdet"):
        return 2
    if opname in ("quantize", "quantize_v2", "requantize") or \
            opname.startswith("_contrib_quantized_"):
        # every quantized-lattice op emits (data, min, max)
        # (reference: src/operator/quantization/*.cc num_outputs=3)
        return 3
    return 1


def _make_node(opname, inputs, kwargs, name=None):
    from .. import attribute, name as _name_mod

    if name is None:
        # per-hint counters + Prefix scoping (reference: every symbol
        # creation resolves its name through NameManager.current)
        name = _name_mod.current().get(None, opname.lower())
    node = Symbol(op=opname, name=name, inputs=inputs, kwargs=kwargs,
                  num_outputs=_num_outputs_for(opname, kwargs))
    scope_attrs = attribute.current().get(None)
    if scope_attrs:
        node._attrs.update(scope_attrs)
    return node


# op -> tensor-parameter inputs auto-created when omitted (reference:
# each op's NNVM ListInputNames; composition fills missing inputs with
# variables named {node}_{input})
# op -> input positions that are auxiliary states. Aux-ness is a property
# of the graph STRUCTURE (the reference derives it from each op's
# FMutateInputs, nnvm has no aux marker in JSON) — so it is re-derived
# whenever a node is built: by the op wrappers AND by the JSON loader.
_AUX_INPUT_SLOTS = {"batch_norm": (3, 4)}


def _default_aux_array(name, shape):
    """Bind-time default for an aux state: variances start at ONE
    (rsqrt(0) would blow up), means/others at zero — the reference's
    BatchNorm aux initialization."""
    from .. import ndarray as _ndmod

    return _ndmod.ones(shape) if name.endswith("var") \
        else _ndmod.zeros(shape)


def _mark_aux_inputs(node):
    slots = _AUX_INPUT_SLOTS.get(node._op)
    if not slots:
        return
    for idx in slots:
        if idx < len(node._inputs):
            v = node._inputs[idx]
            if v._op is None and v._group is None:
                v._attrs.setdefault("__aux__", "1")

_AUTO_PARAMS = {
    "fully_connected": ("weight", "bias"),
    "convolution": ("weight", "bias"),
    "deconvolution": ("weight", "bias"),
    "embedding": ("weight",),
    "batch_norm": ("gamma", "beta", "moving_mean", "moving_var"),
    "layer_norm": ("gamma", "beta"),
    "group_norm": ("gamma", "beta"),
    "instance_norm": ("gamma", "beta"),
}


def _sym_wrapper(opdef):
    import inspect

    sig = inspect.signature(opdef.fn)
    sig_names = [p.name for p in sig.parameters.values()
                 if p.kind in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY)]
    # ops like deconvolution default no_bias=True — the auto-created
    # bias must respect the signature default, not just explicit kwargs
    _nb = sig.parameters.get("no_bias")
    no_bias_default = bool(_nb.default) if _nb is not None and \
        _nb.default is not inspect.Parameter.empty else False

    def wrapper(*args, **kwargs):
        from .. import name as _name_mod

        # resolve the node name exactly once: explicit names pass
        # through (Prefix scopes prepend), None draws a per-hint counter
        name = _name_mod.current().get(kwargs.pop("name", None),
                                       opdef.name.lower())
        attr = kwargs.pop("attr", None)
        # bind positional args (Symbol or config) to signature names, then
        # split into Symbol inputs (kept in signature order) and config
        bound = {}
        for i, a in enumerate(args):
            if i < len(sig_names):
                bound[sig_names[i]] = a
            elif isinstance(a, Symbol):
                bound[f"__extra{i}"] = a  # varargs ops (concat, stack, ...)
        bound.update(kwargs)
        # auto-create missing parameter inputs as Variables named
        # {node}_{arg} like the reference's NNVM composition (symbol.py:
        # FullyConnected(data, num_hidden=8) creates fc_weight/fc_bias).
        # Only fires when a real Symbol input was given, and skips bias
        # under no_bias=True (the input doesn't exist then).
        auto = _AUTO_PARAMS.get(opdef.name)
        has_sym = any(isinstance(v, Symbol) for v in bound.values())
        if auto and has_sym:
            no_bias = bool(bound.get("no_bias", no_bias_default))
            for key in auto:
                if key in bound:
                    continue
                if key == "bias" and no_bias:
                    continue
                # aux-ness is applied structurally by _mark_aux_inputs
                # on the finished node (single source of truth)
                bound[key] = Variable(f"{name}_{key}")
        inputs, config = [], {}
        for key in sig_names:
            if key in bound:
                v = bound.pop(key)
                if isinstance(v, Symbol):
                    inputs.append(v)
                elif v is not None:
                    config[key] = v
        for key, v in bound.items():
            if isinstance(v, Symbol):
                inputs.append(v)
            else:
                config[key] = v
        node = _make_node(opdef.name, inputs, config, name=name)
        _mark_aux_inputs(node)  # structural aux-ness (FMutateInputs)
        if attr:
            node._set_attr(**attr)
        return node

    wrapper.__name__ = opdef.name
    wrapper.__doc__ = opdef.doc
    return wrapper


def _populate():
    mod = _sys.modules[__name__]
    for name in _registry.list_ops():
        if not hasattr(mod, name):
            setattr(mod, name, _sym_wrapper(_registry.get_op(name)))
    from ..ndarray import _CAMEL_ALIASES

    for alias, target in _CAMEL_ALIASES.items():
        if not hasattr(mod, alias) and hasattr(mod, target):
            setattr(mod, alias, getattr(mod, target))


_populate()

# `mx.sym.linalg` / `mx.sym.image` namespaces (reference:
# python/mxnet/symbol/{linalg,image}.py — prefix-stripped autogen)
import types as _types  # noqa: E402


def _sym_prefix_namespace(short):
    mod = _types.ModuleType(__name__ + "." + short)
    pre = short + "_"
    for name in _registry.list_ops():
        if name.startswith(pre):
            setattr(mod, name[len(pre):],
                    _sym_wrapper(_registry.get_op(name)))
    _sys.modules[mod.__name__] = mod
    return mod


linalg = _sym_prefix_namespace("linalg")
image = _sym_prefix_namespace("image")

# `mx.sym.contrib` namespace (reference: python/mxnet/symbol/contrib.py):
# same op set as nd.contrib, emitting graph nodes
contrib = _types.ModuleType(__name__ + ".contrib")
from ..ndarray.contrib import _CONTRIB_OPS, _CONTRIB_ALIASES  # noqa: E402

for _cname in _CONTRIB_OPS:
    _cdef = _registry.get_op(_cname) or _registry.get_op(_cname.lower())
    if _cdef is None:  # fail-fast like nd.contrib._install
        raise RuntimeError(f"contrib op '{_cname}' listed but unregistered")
    setattr(contrib, _cname, _sym_wrapper(_cdef))
for _alias, _target in _CONTRIB_ALIASES.items():
    setattr(contrib, _alias, getattr(contrib, _target))
_sys.modules[contrib.__name__] = contrib


def zeros(shape, dtype="float32", **kwargs):
    return _make_node("_sym_zeros", [], {"shape": shape, "dtype": dtype})


def ones(shape, dtype="float32", **kwargs):
    return _make_node("_sym_ones", [], {"shape": shape, "dtype": dtype})


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def _parse_attr_value(v):
    """Parse an MXNet-stringified attr ("(3, 3)", "True", "2", "0.9",
    "relu") back to a python value."""
    if not isinstance(v, str):
        return v
    import ast

    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        pass
    low = v.strip().lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    return v


def load_json(json_str):
    """Rebuild a Symbol DAG from tojson output — accepts both this
    package's emission and reference-era nnvm JSON (CamelCase legacy op
    names, stringified attrs, "attr"/"param" instead of "attrs" in very
    old files). Reference: nnvm/src/core/graph.cc JSON load + SURVEY §7
    step 8 checkpoint-interop requirement."""
    import inspect

    from ..ndarray import registry as _reg

    obj = json.loads(json_str)
    nodes = obj["nodes"]
    legacy = "mxnet_tpu_version" in obj  # round-1/2 own-format files
    built = []
    for n in nodes:
        if n["op"] == "null":
            # Symbol directly, NOT Variable(): the file's attrs are the
            # whole truth — an ambient AttrScope must not stamp extra
            # attrs onto a deserialized graph (the reference's C-API
            # load never consults AttrScope)
            v = Symbol(op=None, name=n["name"])
            v._attrs.update({k: str(a) for k, a in
                             (n.get("attrs") or {}).items()})
            built.append(v)
            continue
        inputs = []
        for entry in n["inputs"]:
            i, oi = entry[0], entry[1]
            src = built[i]
            src = src if oi == 0 else src[oi]
            inputs.append(src)
        opname = n["op"]
        attrs = n.get("attrs", n.get("attr", n.get("param", {}))) or {}
        kwargs = {}
        if legacy:
            for k, v in attrs.items():
                try:
                    kwargs[k] = json.loads(v)
                except (json.JSONDecodeError, TypeError):
                    kwargs[k] = v
        else:
            opdef = _reg.get_op(opname)
            if opdef is None:
                # legacy CamelCase name → registered snake_case op
                from ..ndarray import _CAMEL_ALIASES

                mapped = _CAMEL_ALIASES.get(opname)
                if mapped is None or _reg.get_op(mapped) is None:
                    raise MXNetError(
                        f"unknown op '{opname}' in symbol JSON")
                opname = mapped
                opdef = _reg.get_op(opname)
            # keep only attrs the op body understands (reference files
            # carry backend knobs like workspace/cudnn_tune)
            sig = inspect.signature(opdef.fn)
            accepts_kw = any(p.kind == p.VAR_KEYWORD
                             for p in sig.parameters.values())
            known = set(sig.parameters)
            for k, v in attrs.items():
                if (accepts_kw or k in known) and not k.startswith("__"):
                    kwargs[k] = _parse_attr_value(v)
        node = Symbol(op=opname, name=n["name"], inputs=inputs,
                      kwargs=kwargs,
                      num_outputs=n.get(
                          "num_outputs",
                          _num_outputs_for(opname, kwargs)))
        # non-parameter keys (user attrs, dunder hyperparams, backend
        # knobs from reference files) are preserved as symbol attrs
        node._attrs.update({k: str(v) for k, v in attrs.items()
                            if k not in kwargs})
        _mark_aux_inputs(node)
        built.append(node)
    heads = [built[i] if h[1] == 0 else built[i][h[1]]
             for h in obj["heads"] for i in [h[0]]]
    return heads[0] if len(heads) == 1 else Group(heads)
