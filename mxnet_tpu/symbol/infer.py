"""Partial shape inference over a Symbol DAG.

TPU-native equivalent of the reference's graph shape-inference pass
(reference: src/executor/infer_graph_attr_pass.cc:360-661 — forward
FInferShape with partial info). Per node: unknown *parameter* input shapes
are derived from layer semantics (the FInferShape each NN op registers in
the reference), then the node's output shape comes from
``jax.eval_shape`` over the op's pure-JAX body — the op body IS its shape
function, so there is no second shape-rule registry to keep in sync.
"""
from __future__ import annotations

import inspect

import numpy as onp

import jax

from ..base import MXNetError
from ..ndarray import registry as _registry


def _prod(xs):
    out = 1
    for x in xs:
        out *= x
    return out


def _param_shape_rules(op, kw, in_shapes, arg_names):
    """Given known data shape (index 0), return {input_idx: shape} for
    unknown parameter inputs. Mirrors the reference ops' FInferShape."""
    data = in_shapes.get(0)
    if data is None:
        return {}
    out = {}

    def named(name):
        return arg_names.index(name) if name in arg_names else None

    def range_scalars():
        # offline-quantized range variables (`*_min` / `*_max`) are
        # (1,)-shaped, matching quantize_model's nd.array([±amax])
        for r in ("min_data", "max_data", "min_weight", "max_weight",
                  "min_bias", "max_bias"):
            if named(r) is not None:
                out[named(r)] = (1,)

    if op in ("fully_connected", "_contrib_quantized_fully_connected"):
        num_hidden = kw.get("num_hidden")
        flatten = kw.get("flatten", True)
        in_units = _prod(data[1:]) if flatten else data[-1]
        out[named("weight")] = (num_hidden, in_units)
        if named("bias") is not None:
            out[named("bias")] = (num_hidden,)
        if op.startswith("_contrib_quantized_"):
            range_scalars()
    elif op in ("convolution", "_contrib_quantized_conv"):
        kernel = tuple(kw.get("kernel"))
        nf = kw.get("num_filter")
        g = kw.get("num_group", 1)
        out[named("weight")] = (nf, data[1] // g) + kernel
        if named("bias") is not None:
            out[named("bias")] = (nf,)
        if op.startswith("_contrib_quantized_"):
            range_scalars()
    elif op == "deconvolution":
        kernel = tuple(kw.get("kernel"))
        nf = kw.get("num_filter")
        g = kw.get("num_group", 1)
        out[named("weight")] = (data[1], nf // g) + kernel
        if named("bias") is not None:
            out[named("bias")] = (nf,)
    elif op in ("batch_norm", "_contrib_quantized_batch_norm"):
        # quantized BN is only formed for axis=1 (the pass gates on it)
        axis = kw.get("axis", 1)
        c = (data[axis],)
        for pname in ("gamma", "beta", "moving_mean", "moving_var"):
            idx = named(pname)
            if idx is not None:
                out[idx] = c
    elif op in ("layer_norm",):
        axis = kw.get("axis", -1)
        c = (data[axis],)
        out[named("gamma")] = c
        out[named("beta")] = c
    elif op in ("instance_norm", "group_norm"):
        c = (data[1],)
        out[named("gamma")] = c
        out[named("beta")] = c
    elif op == "embedding":
        out[named("weight")] = (kw.get("input_dim"), kw.get("output_dim"))
    elif op == "rnn":
        from ..ndarray.ops_nn import rnn_param_size

        size = rnn_param_size(kw.get("num_layers", 1), data[-1],
                              kw.get("state_size"),
                              kw.get("bidirectional", False),
                              kw.get("mode", "lstm"))
        out[named("parameters")] = (size,)
        D = 2 if kw.get("bidirectional", False) else 1
        st = (kw.get("num_layers", 1) * D, data[1], kw.get("state_size"))
        if named("state") is not None:
            out[named("state")] = st
        if named("state_cell") is not None:
            out[named("state_cell")] = st
    elif op in ("leaky_relu",) and kw.get("act_type") == "prelu":
        out[named("gamma")] = (data[1] if len(data) > 1 else 1,)
    elif op == "softmax_output":
        # label shape = data shape without the class axis (reference
        # softmax_output.cc FInferShape) — lets the C predict API bind
        # exported training graphs with only `data` provided.
        # multi_output mode softmaxes axis 1: label is (N, *spatial)
        if kw.get("multi_output"):
            out[named("label")] = (data[0],) + tuple(data[2:])
        else:
            out[named("label")] = tuple(data[:-1])
    elif op == "svm_output":
        # class-index labels like softmax_output (reference svm_output.cc)
        out[named("label")] = tuple(data[:-1])
    elif op in ("linear_regression_output", "mae_regression_output",
                "logistic_regression_output"):
        out[named("label")] = tuple(data)
    return {k: v for k, v in out.items() if k is not None}


def _array_arg_names(opdef):
    sig = inspect.signature(opdef.fn)
    return [p.name for p in sig.parameters.values()
            if p.kind in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY)]


#: quantized MXU ops accumulate via preferred_element_type=int32, which
#: XLA only accepts over integer operands — their eval_shape specs must
#: be int8 at (data, weight). Every other op is shape-polymorphic over
#: dtype, so the fp32 default stays (and stays bit-identical for
#: existing fp32 graphs).
_INT8_SPEC_SLOTS = {
    "_contrib_quantized_conv": (0, 1),
    "_contrib_quantized_fully_connected": (0, 1),
    "_contrib_quantized_batch_dot": (0, 1),
}


def _spec_dtype(op, idx):
    if idx in _INT8_SPEC_SLOTS.get(op, ()):
        return onp.int8
    return onp.float32


def infer_shapes(symbol, known, allow_unknown=False,
                 return_node_shapes=False):
    """Walk the DAG; return ({var_name: shape}, [output shapes]).

    `known` maps variable names to shapes. Unknown parameter shapes are
    filled by layer rules; raises if a needed shape stays unknown
    (unless allow_unknown). With ``return_node_shapes`` the per-node
    table (``id(node) -> shape | list-of-shapes``) rides along as a
    third element — the fusion cost model prices clusters off it
    without a second walk.
    """
    order = symbol._walk()
    var_shapes = dict(known)
    node_out = {}  # id(node) -> shape or list-of-shapes

    for node in order:
        if node._group is not None:
            continue
        if node._op is None:
            if node._name in var_shapes:
                node_out[id(node)] = tuple(var_shapes[node._name])
            continue
        if node._op in ("_sym_zeros", "_sym_ones", "_sym_constant"):
            # literal-shaped constants (sym.zeros / sym.ones / folded)
            node_out[id(node)] = tuple(node._kwargs["shape"])
            continue
        opdef = _registry.get_op(node._op)
        if opdef is None:
            raise MXNetError(f"op '{node._op}' is not registered")
        arg_names = _array_arg_names(opdef)
        in_shapes = {}
        for i, inp in enumerate(node._inputs):
            s = node_out.get(id(inp))
            if isinstance(s, list):
                s = s[inp._output_index]
            if s is not None:
                in_shapes[i] = s
        # fill unknown parameter-var inputs via layer rules
        if len(in_shapes) < len(node._inputs):
            rules = _param_shape_rules(node._op, node._kwargs, in_shapes,
                                       arg_names)
            for i, inp in enumerate(node._inputs):
                if i in in_shapes:
                    continue
                if inp._op is None and i in rules:
                    var_shapes[inp._name] = tuple(rules[i])
                    node_out[id(inp)] = tuple(rules[i])
                    in_shapes[i] = tuple(rules[i])
        if len(in_shapes) < len(node._inputs):
            if allow_unknown:
                continue
            missing = [node._inputs[i]._name for i in
                       range(len(node._inputs)) if i not in in_shapes]
            raise MXNetError(
                f"cannot infer shape for inputs {missing} of op "
                f"'{node._op}' ({node._name})")

        specs = [jax.ShapeDtypeStruct(in_shapes[i],
                                      _spec_dtype(node._op, i))
                 for i in range(len(node._inputs))]
        kwargs = dict(node._kwargs)

        def f(*xs):
            return opdef.fn(*xs, **kwargs)

        try:
            o = jax.eval_shape(f, *specs)
        except Exception as e:
            raise MXNetError(
                f"shape inference failed at op '{node._op}' "
                f"({node._name}) with input shapes "
                f"{[tuple(s.shape) for s in specs]}: {e}") from e
        if isinstance(o, (list, tuple)):
            node_out[id(node)] = [tuple(x.shape) for x in o]
        else:
            node_out[id(node)] = tuple(o.shape)

    heads = symbol._group if symbol._group else [symbol]
    out_shapes = []
    for h in heads:
        s = node_out.get(id(h))
        if isinstance(s, list):
            s = s[h._output_index]
        out_shapes.append(s)
    if return_node_shapes:
        return var_shapes, out_shapes, node_out
    return var_shapes, out_shapes


# ---- dtype inference -------------------------------------------------------

def _canon(d):
    # runtime-truthful: under jax's default x64-off config, 64-bit tags
    # execute as their 32-bit types — report what execution produces
    return onp.dtype(jax.dtypes.canonicalize_dtype(onp.dtype(d)))


# ops whose output dtype is fixed rather than promoted from inputs
# (reference: per-op FInferType registrations)
_FIXED_OUT_DTYPE = {
    "argmax": onp.float32, "argmin": onp.float32,
    "shape_array": onp.int64, "size_array": onp.int64,
    "dequantize": onp.float32,
}

# ops whose non-data variable inputs have a fixed default dtype instead
# of the same-type sibling constraint (reference FInferType specifics).
# Quantized conv/fc weight variables (`*_quantized`, offline weight
# quantization) are int8 by construction — without the entry the
# sibling constraint would promote them to the fp32 of the range inputs
_PARAM_DTYPE_DEFAULTS = {
    "embedding": {1: onp.float32},
    "_contrib_quantized_conv": {1: onp.int8},
    "_contrib_quantized_fully_connected": {1: onp.int8},
}

#: quantized int32-accumulator ops (a following requantize narrows)
_QUANT_ACC_OPS = ("_contrib_quantized_conv",
                  "_contrib_quantized_fully_connected",
                  "_contrib_quantized_batch_dot")
#: quantized ops whose payload output is int8 on a fresh lattice
_QUANT_S8_OPS = ("_contrib_quantized_elemwise_add",
                 "_contrib_quantized_concat",
                 "_contrib_quantized_batch_norm")
#: quantized ops that pass the input lattice (int8 OR uint8) through
_QUANT_PASSTHROUGH_OPS = ("_contrib_quantized_act",
                          "_contrib_quantized_flatten",
                          "_contrib_quantized_pooling")


def _node_out_dtype(op, kw, in_dtypes):
    if op in ("cast", "amp_cast"):
        return _canon(kw.get("dtype", "float32"))
    if op in _FIXED_OUT_DTYPE:
        return _canon(_FIXED_OUT_DTYPE[op])
    if op in ("quantize", "quantize_v2"):
        # (q, min, max): quantized payload in out_type, fp32 ranges
        q = _canon(kw.get("out_type",
                          "uint8" if op == "quantize" else "int8"))
        return [q, onp.dtype(onp.float32), onp.dtype(onp.float32)]
    if op == "requantize":
        return [_canon(kw.get("out_type", "int8")),
                onp.dtype(onp.float32), onp.dtype(onp.float32)]
    f32 = onp.dtype(onp.float32)
    if op in _QUANT_ACC_OPS:
        return [onp.dtype(onp.int32), f32, f32]
    if op in _QUANT_S8_OPS:
        return [onp.dtype(onp.int8), f32, f32]
    if op in _QUANT_PASSTHROUGH_OPS:
        return [onp.dtype(in_dtypes.get(0, onp.int8)), f32, f32]
    if op in ("_sym_zeros", "_sym_ones", "_sym_constant"):
        return _canon(kw.get("dtype", "float32"))
    if op == "embedding":
        return in_dtypes.get(1, onp.dtype(onp.float32))  # weight dtype
    if not in_dtypes:
        return onp.dtype(onp.float32)
    import jax.numpy as jnp

    return onp.dtype(jnp.result_type(*[onp.dtype(d)
                                       for d in in_dtypes.values()]))


def infer_types(symbol, known):
    """Forward dtype propagation (reference:
    infer_graph_attr_pass.cc with FInferType; most ops are
    ElemwiseType — same dtype in, promoted dtype out). `known` maps
    variable names to dtypes; unknown parameter variables inherit the
    promoted dtype of their node's known siblings (the reference's
    bidirectional same-type constraint, forward half).
    """
    var_types = {k: onp.dtype(v) for k, v in known.items()}
    node_out = {}
    for node in symbol._walk():
        if node._group is not None:
            continue
        if node._op is None:
            if node._name in var_types:
                node_out[id(node)] = var_types[node._name]
            continue
        in_dtypes = {}
        for i, inp in enumerate(node._inputs):
            d = node_out.get(id(inp))
            if isinstance(d, list):  # multi-output producer: pick ours
                d = d[inp._output_index] if inp._output_index < len(d) \
                    else d[-1]
            if d is not None:
                in_dtypes[i] = d
        # op-specific parameter defaults first (embedding weight is fp32
        # regardless of the integer index dtype), then the promoted
        # same-type sibling constraint for the rest
        defaults = _PARAM_DTYPE_DEFAULTS.get(node._op, {})
        for i, inp in enumerate(node._inputs):
            if i not in in_dtypes and inp._op is None and i in defaults:
                var_types.setdefault(inp._name, onp.dtype(defaults[i]))
                node_out[id(inp)] = var_types[inp._name]
                in_dtypes[i] = var_types[inp._name]
        if in_dtypes and len(in_dtypes) < len(node._inputs):
            import jax.numpy as jnp

            sib = onp.dtype(jnp.result_type(
                *[onp.dtype(d) for d in in_dtypes.values()]))
            for i, inp in enumerate(node._inputs):
                if i not in in_dtypes and inp._op is None:
                    var_types.setdefault(inp._name, sib)
                    node_out[id(inp)] = var_types[inp._name]
                    in_dtypes[i] = var_types[inp._name]
        out_d = _node_out_dtype(node._op, node._kwargs, in_dtypes)
        node_out[id(node)] = out_d
    heads = symbol._group if symbol._group else [symbol]
    out_types = []
    for h in heads:
        d = node_out.get(id(h), onp.dtype(onp.float32))
        # one dtype per list_outputs() entry (multi-output nodes list
        # every output, so the dtype list expands in lockstep)
        n = getattr(h, "_num_outputs", 1) or 1
        if isinstance(d, list):
            out_types.extend(list(d[:n]) + [d[-1]] * max(0, n - len(d)))
        else:
            out_types.extend([d] * n)
    return var_types, out_types
