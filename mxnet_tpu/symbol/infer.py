"""Partial shape inference over a Symbol DAG.

TPU-native equivalent of the reference's graph shape-inference pass
(reference: src/executor/infer_graph_attr_pass.cc:360-661 — forward
FInferShape with partial info). Per node: unknown *parameter* input shapes
are derived from layer semantics (the FInferShape each NN op registers in
the reference), then the node's output shape comes from
``jax.eval_shape`` over the op's pure-JAX body — the op body IS its shape
function, so there is no second shape-rule registry to keep in sync.
"""
from __future__ import annotations

import inspect

import numpy as onp

import jax

from ..base import MXNetError
from ..ndarray import registry as _registry


def _prod(xs):
    out = 1
    for x in xs:
        out *= x
    return out


def _param_shape_rules(op, kw, in_shapes, arg_names):
    """Given known data shape (index 0), return {input_idx: shape} for
    unknown parameter inputs. Mirrors the reference ops' FInferShape."""
    data = in_shapes.get(0)
    if data is None:
        return {}
    out = {}

    def named(name):
        return arg_names.index(name) if name in arg_names else None

    if op == "fully_connected":
        num_hidden = kw.get("num_hidden")
        flatten = kw.get("flatten", True)
        in_units = _prod(data[1:]) if flatten else data[-1]
        out[named("weight")] = (num_hidden, in_units)
        if named("bias") is not None:
            out[named("bias")] = (num_hidden,)
    elif op == "convolution":
        kernel = tuple(kw.get("kernel"))
        nf = kw.get("num_filter")
        g = kw.get("num_group", 1)
        out[named("weight")] = (nf, data[1] // g) + kernel
        if named("bias") is not None:
            out[named("bias")] = (nf,)
    elif op == "deconvolution":
        kernel = tuple(kw.get("kernel"))
        nf = kw.get("num_filter")
        g = kw.get("num_group", 1)
        out[named("weight")] = (data[1], nf // g) + kernel
        if named("bias") is not None:
            out[named("bias")] = (nf,)
    elif op in ("batch_norm",):
        axis = kw.get("axis", 1)
        c = (data[axis],)
        for pname in ("gamma", "beta", "moving_mean", "moving_var"):
            idx = named(pname)
            if idx is not None:
                out[idx] = c
    elif op in ("layer_norm",):
        axis = kw.get("axis", -1)
        c = (data[axis],)
        out[named("gamma")] = c
        out[named("beta")] = c
    elif op in ("instance_norm", "group_norm"):
        c = (data[1],)
        out[named("gamma")] = c
        out[named("beta")] = c
    elif op == "embedding":
        out[named("weight")] = (kw.get("input_dim"), kw.get("output_dim"))
    elif op == "rnn":
        from ..ndarray.ops_nn import rnn_param_size

        size = rnn_param_size(kw.get("num_layers", 1), data[-1],
                              kw.get("state_size"),
                              kw.get("bidirectional", False),
                              kw.get("mode", "lstm"))
        out[named("parameters")] = (size,)
        D = 2 if kw.get("bidirectional", False) else 1
        st = (kw.get("num_layers", 1) * D, data[1], kw.get("state_size"))
        if named("state") is not None:
            out[named("state")] = st
        if named("state_cell") is not None:
            out[named("state_cell")] = st
    elif op in ("leaky_relu",) and kw.get("act_type") == "prelu":
        out[named("gamma")] = (data[1] if len(data) > 1 else 1,)
    elif op == "softmax_output":
        # label shape = data shape without the class axis (reference
        # softmax_output.cc FInferShape) — lets the C predict API bind
        # exported training graphs with only `data` provided.
        # multi_output mode softmaxes axis 1: label is (N, *spatial)
        if kw.get("multi_output"):
            out[named("label")] = (data[0],) + tuple(data[2:])
        else:
            out[named("label")] = tuple(data[:-1])
    elif op == "svm_output":
        # class-index labels like softmax_output (reference svm_output.cc)
        out[named("label")] = tuple(data[:-1])
    elif op in ("linear_regression_output", "mae_regression_output",
                "logistic_regression_output"):
        out[named("label")] = tuple(data)
    return {k: v for k, v in out.items() if k is not None}


def _array_arg_names(opdef):
    sig = inspect.signature(opdef.fn)
    return [p.name for p in sig.parameters.values()
            if p.kind in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY)]


def infer_shapes(symbol, known, allow_unknown=False):
    """Walk the DAG; return ({var_name: shape}, [output shapes]).

    `known` maps variable names to shapes. Unknown parameter shapes are
    filled by layer rules; raises if a needed shape stays unknown
    (unless allow_unknown).
    """
    order = symbol._walk()
    var_shapes = dict(known)
    node_out = {}  # id(node) -> shape or list-of-shapes

    for node in order:
        if node._group is not None:
            continue
        if node._op is None:
            if node._name in var_shapes:
                node_out[id(node)] = tuple(var_shapes[node._name])
            continue
        if node._op in ("_sym_zeros", "_sym_ones"):
            # literal-shaped constants (sym.zeros / sym.ones)
            node_out[id(node)] = tuple(node._kwargs["shape"])
            continue
        opdef = _registry.get_op(node._op)
        if opdef is None:
            raise MXNetError(f"op '{node._op}' is not registered")
        arg_names = _array_arg_names(opdef)
        in_shapes = {}
        for i, inp in enumerate(node._inputs):
            s = node_out.get(id(inp))
            if isinstance(s, list):
                s = s[inp._output_index]
            if s is not None:
                in_shapes[i] = s
        # fill unknown parameter-var inputs via layer rules
        if len(in_shapes) < len(node._inputs):
            rules = _param_shape_rules(node._op, node._kwargs, in_shapes,
                                       arg_names)
            for i, inp in enumerate(node._inputs):
                if i in in_shapes:
                    continue
                if inp._op is None and i in rules:
                    var_shapes[inp._name] = tuple(rules[i])
                    node_out[id(inp)] = tuple(rules[i])
                    in_shapes[i] = tuple(rules[i])
        if len(in_shapes) < len(node._inputs):
            if allow_unknown:
                continue
            missing = [node._inputs[i]._name for i in
                       range(len(node._inputs)) if i not in in_shapes]
            raise MXNetError(
                f"cannot infer shape for inputs {missing} of op "
                f"'{node._op}' ({node._name})")

        specs = [jax.ShapeDtypeStruct(in_shapes[i], onp.float32)
                 for i in range(len(node._inputs))]
        kwargs = dict(node._kwargs)

        def f(*xs):
            return opdef.fn(*xs, **kwargs)

        try:
            o = jax.eval_shape(f, *specs)
        except Exception as e:
            raise MXNetError(
                f"shape inference failed at op '{node._op}' "
                f"({node._name}) with input shapes "
                f"{[tuple(s.shape) for s in specs]}: {e}") from e
        if isinstance(o, (list, tuple)):
            node_out[id(node)] = [tuple(x.shape) for x in o]
        else:
            node_out[id(node)] = tuple(o.shape)

    heads = symbol._group if symbol._group else [symbol]
    out_shapes = []
    for h in heads:
        s = node_out.get(id(h))
        if isinstance(s, list):
            s = s[h._output_index]
        out_shapes.append(s)
    return var_shapes, out_shapes
