/* Flat C ABI for the mxnet_tpu runtime.
 *
 * Reference surface: include/mxnet/c_api.h and c_predict_api.h of the
 * upstream project. Every function returns 0 on success and -1 on
 * failure; call MXGetLastError() for the message (valid until the next
 * failing call on the same thread).
 *
 * Link against libmxnet_c.so (built by `make c_api` in native/). The
 * library attaches to the calling process's Python interpreter when one
 * is live (e.g. loaded via ctypes), or embeds one on first use from a
 * standalone C/C++ application — in that case make sure PYTHONPATH
 * reaches the mxnet_tpu package.
 */
#ifndef MXNET_TPU_C_API_H_
#define MXNET_TPU_C_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define MX_MAX_DIM 8

typedef void* NDArrayHandle;
typedef void* PredictorHandle;

/* dtype flags (mshadow type flags, reference include/mxnet/base.h):
 * 0=float32 1=float64 2=float16 3=uint8 4=int32 5=int8 6=int64 7=bool */

int MXGetVersion(int* out);
const char* MXGetLastError(void);

int MXNDArrayCreate(const int64_t* shape, int ndim, int dtype,
                    NDArrayHandle* out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArrayGetShape(NDArrayHandle handle, int* out_ndim,
                      int64_t* out_shape /* int64_t[MX_MAX_DIM] */);
int MXNDArrayGetDType(NDArrayHandle handle, int* out);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t nbytes);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t nbytes);
int MXNDArrayWaitAll(void);

/* Run a registered operator by name. Param values are stringified the
 * same way the reference C API expects ("(3, 3)", "True", "relu").
 * *outputs points at thread-local storage owned by the library and valid
 * until this thread's next MXImperativeInvoke; do NOT call
 * MXNDArrayFree on the returned output handles. */
int MXImperativeInvoke(const char* op_name, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals);

/* ---- C predict API (deploy-only inference) --------------------------- */

int MXPredCreate(const char* symbol_json, const void* param_bytes,
                 size_t param_size, int dev_type, int dev_id,
                 uint32_t num_input, const char** input_keys,
                 const uint32_t* input_shape_indptr,
                 const int64_t* input_shape_data, PredictorHandle* out);
int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, uint32_t size /* #floats */);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         int* out_ndim,
                         int64_t* out_shape /* int64_t[MX_MAX_DIM] */);
int MXPredGetOutput(PredictorHandle handle, uint32_t index, float* data,
                    uint32_t size /* #floats */);
int MXPredFree(PredictorHandle handle);

/* ---- Symbol API (graph construction; c_api_symbolic.cc surface) ------ */

typedef void* SymbolHandle;

int MXSymbolCreateVariable(const char* name, SymbolHandle* out);
/* Creates an operator with bound params; attach inputs with
 * MXSymbolCompose before binding. Param values are stringified like the
 * reference ("4", "relu", "(3, 3)"). */
int MXSymbolCreateAtomicSymbol(const char* op_name, uint32_t num_param,
                               const char** keys, const char** vals,
                               SymbolHandle* out);
/* Composes in place: after this call `sym` is the finished graph node.
 * keys == NULL means positional inputs. */
int MXSymbolCompose(SymbolHandle sym, const char* name, uint32_t num_args,
                    const char** keys, SymbolHandle* args);
int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out);
/* *out_json points at thread-local storage valid until this thread's
 * next MXSymbolSaveToJSON. */
int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json);
/* Name arrays point at thread-local storage valid until this thread's
 * next MXSymbolList* call. */
int MXSymbolListArguments(SymbolHandle sym, uint32_t* out_size,
                          const char*** out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle sym, uint32_t* out_size,
                                const char*** out_array);
int MXSymbolListOutputs(SymbolHandle sym, uint32_t* out_size,
                        const char*** out_array);
int MXSymbolFree(SymbolHandle sym);

/* ---- Executor API (training; c_api_executor.cc surface) -------------- */

typedef void* ExecutorHandle;

/* grad_req: "write" | "add" | "null". Shapes use the same CSR layout as
 * MXPredCreate. */
int MXExecutorSimpleBind(SymbolHandle sym, const char* grad_req,
                         uint32_t num_input, const char** input_keys,
                         const uint32_t* input_shape_indptr,
                         const int64_t* input_shape_data,
                         ExecutorHandle* out);
/* Borrow a bound array: kind "arg" | "grad" | "aux". The handle aliases
 * executor storage (copy into it to feed the next forward) and must be
 * released with MXNDArrayFree. */
int MXExecutorArgArray(ExecutorHandle exec, const char* kind,
                       const char* name, NDArrayHandle* out);
int MXExecutorForward(ExecutorHandle exec, int is_train);
/* Output array points at the same thread-local storage as
 * MXImperativeInvoke; do not free the handles. */
int MXExecutorOutputs(ExecutorHandle exec, int* num_outputs,
                      NDArrayHandle** outputs);
/* Gradients of the bound loss head(s) land in the "grad" arrays. */
int MXExecutorBackward(ExecutorHandle exec);
int MXExecutorFree(ExecutorHandle exec);

/* ---- KVStore API (c_api.cc MXKVStore* surface) ----------------------- */

typedef void* KVStoreHandle;

int MXKVStoreCreate(const char* type /* "local" | "device" | ... */,
                    KVStoreHandle* out);
int MXKVStoreSetOptimizer(KVStoreHandle kv, const char* opt_name,
                          uint32_t num_param, const char** keys,
                          const char** vals);
int MXKVStoreInit(KVStoreHandle kv, uint32_t num, const int* keys,
                  NDArrayHandle* vals);
int MXKVStorePush(KVStoreHandle kv, uint32_t num, const int* keys,
                  NDArrayHandle* vals, int priority);
/* Pulls INTO the given arrays in place. */
int MXKVStorePull(KVStoreHandle kv, uint32_t num, const int* keys,
                  NDArrayHandle* outs, int priority);
int MXKVStoreFree(KVStoreHandle kv);

/* ---- misc surface ---------------------------------------------------- */

/* In-place reshape keeping loaded weights+aux; *out is the same handle
 * with its refcount bumped (free both). Reference: MXPredReshape. */
int MXPredReshape(uint32_t num_input, const char** input_keys,
                  const uint32_t* input_shape_indptr,
                  const int64_t* input_shape_data, PredictorHandle handle,
                  PredictorHandle* out);
int MXNDArrayReshape(NDArrayHandle handle, int ndim, const int64_t* shape,
                     NDArrayHandle* out);
int MXNDArraySlice(NDArrayHandle handle, int64_t begin, int64_t end,
                   NDArrayHandle* out);
/* *out_value points at thread-local storage (same buffer as
 * MXSymbolSaveToJSON); out_success is 0 when the attr is unset. */
int MXSymbolGetAttr(SymbolHandle sym, const char* key,
                    const char** out_value, int* out_success);
int MXSymbolSetAttr(SymbolHandle sym, const char* key, const char* value);
int MXKVStoreGetType(KVStoreHandle kv, const char** out_type);
int MXKVStoreGetRank(KVStoreHandle kv, int* out);
int MXKVStoreGetGroupSize(KVStoreHandle kv, int* out);

/* Reference-format .params file IO. keys == NULL saves a bare list.
 * Load returns thread-local storage: the handle array is owned by the
 * library until this thread's next MXNDArrayLoad (do not free), and
 * name pointers share the MXSymbolList* buffer lifetime. */
int MXNDArraySave(const char* fname, uint32_t num, NDArrayHandle* handles,
                  const char** keys);
int MXNDArrayLoad(const char* fname, uint32_t* out_size,
                  NDArrayHandle** out_arr, uint32_t* out_name_size,
                  const char*** out_names);

/* Data iterators (reference: c_api.cc MXDataIter* over src/io/ iters).
 * Params are string key/value pairs; tuple values use Python literal
 * syntax, e.g. data_shape=(3,224,224). GetData/GetLabel return NEW
 * NDArray handles owned by the caller (MXNDArrayFree). */
typedef void* DataIterHandle;
int MXListDataIters(uint32_t* out_size, const char*** out_names);
int MXDataIterCreateIter(const char* name, uint32_t num_param,
                         const char** keys, const char** vals,
                         DataIterHandle* out);
int MXDataIterFree(DataIterHandle it);
int MXDataIterNext(DataIterHandle it, int* out);
int MXDataIterBeforeFirst(DataIterHandle it);
int MXDataIterGetData(DataIterHandle it, NDArrayHandle* out);
int MXDataIterGetLabel(DataIterHandle it, NDArrayHandle* out);
int MXDataIterGetPadNum(DataIterHandle it, int* out);


/* ---- CachedOp (reference: include/mxnet/c_api.h MXCreateCachedOp /
 * MXInvokeCachedOp / MXFreeCachedOp; src/c_api/c_api_ndarray.cc).
 * Inputs are positional in list_arguments()+list_auxiliary_states()
 * order. Output handle array is thread-local like MXImperativeInvoke. */
typedef void* CachedOpHandle;
int MXCreateCachedOp(SymbolHandle sym, CachedOpHandle* out);
int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle* inputs, int* num_outputs,
                     NDArrayHandle** outputs);
int MXFreeCachedOp(CachedOpHandle handle);

/* ---- Autograd (reference: c_api.h MXAutogradSetIsRecording,
 * MXAutogradSetIsTraining, MXAutogradMarkVariables,
 * MXAutogradBackwardEx, MXNDArrayGetGrad). grad_req: 0=null 1=write
 * 2=add. head_grads may be NULL (ones-like seeding). */
int MXAutogradSetIsRecording(int is_recording, int* prev);
int MXAutogradSetIsTraining(int is_training, int* prev);
int MXAutogradMarkVariables(uint32_t num_var, NDArrayHandle* var_handles,
                            uint32_t* grad_reqs,
                            NDArrayHandle* grad_handles);
int MXAutogradBackward(uint32_t num_output, NDArrayHandle* output_handles,
                       NDArrayHandle* head_grad_handles, int retain_graph,
                       int train_mode);
int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle* out);

/* ---- Profiler (reference: c_api.h MXSetProcessProfilerConfig /
 * MXSetProcessProfilerState / MXDumpProcessProfile /
 * MXAggregateProfileStatsPrint; src/c_api/c_api_profile.cc).
 * state: 0=stop 1=run 2=pause. *out_str points at thread-local
 * storage valid until the next stats print on this thread. */
int MXSetProcessProfilerConfig(int num_params, const char** keys,
                               const char** vals);
int MXSetProcessProfilerState(int state);
int MXDumpProcessProfile(int finished);
int MXAggregateProfileStatsPrint(const char** out_str, int reset);

/* Seed the global PRNG (reference: c_api.h MXRandomSeed). */
int MXRandomSeed(int seed);


/* ---- Operator introspection (reference: c_api.h MXListAllOpNames,
 * MXSymbolGetAtomicSymbolInfo). String arrays are thread-local like the
 * MXSymbolList* buffers. */
int MXListAllOpNames(uint32_t* out_size, const char*** out_array);
int MXSymbolGetAtomicSymbolInfo(const char* op_name, const char** name,
                                const char** description,
                                uint32_t* num_args,
                                const char*** arg_names,
                                const char*** arg_default_vals);

/* ---- Shape/type inference (reference: c_api_symbolic.cc
 * MXSymbolInferShape/MXSymbolInferType, flattened-buffer variant).
 * Results: out_sections = [n_args, n_outs, n_aux]; out_ndims one entry
 * per shape in that order (-1 = undetermined); out_dims concatenated.
 * Type flags follow the NDArray dtype codes; -1 = undetermined. */
int MXSymbolInferShape(SymbolHandle sym, uint32_t num_args,
                       const char** keys, const uint32_t* arg_ind_ptr,
                       const int64_t* arg_shape_data, uint32_t* out_total,
                       const int64_t** out_ndims, const int64_t** out_dims,
                       const int64_t** out_sections);
int MXSymbolInferType(SymbolHandle sym, uint32_t num_args,
                      const char** keys, const int* arg_types,
                      uint32_t* out_total, const int** out_types,
                      const int64_t** out_sections);

/* ---- KVStore tail + NDArray misc. */
int MXKVStoreBarrier(KVStoreHandle kv);
int MXKVStorePushPull(KVStoreHandle kv, uint32_t num, const int* keys,
                      NDArrayHandle* vals, NDArrayHandle* outs,
                      int priority);
/* Row view (new handle, caller frees). */
int MXNDArrayAt(NDArrayHandle handle, uint32_t idx, NDArrayHandle* out);
/* dev_type codes: 1=cpu 2=gpu/tpu 3=cpu_pinned 5=cpu_shared. */
int MXNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                        int* out_dev_id);

#ifdef __cplusplus
}
#endif

#endif /* MXNET_TPU_C_API_H_ */
