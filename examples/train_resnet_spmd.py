"""ResNet training on a device mesh with the compiled SPMD path
(reference: example/image-classification/train_imagenet.py +
--benchmark 1, rebuilt around SPMDTrainer instead of kvstore devices).

  python examples/train_resnet_spmd.py --batch 64 --steps 10 --bf16
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/train_resnet_spmd.py --dp 4 --mp 2
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--image-size", type=int, default=96)
    p.add_argument("--classes", type=int, default=100)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--depth", type=int, default=18)
    p.add_argument("--dp", type=int, default=0, help="data-parallel way")
    p.add_argument("--mp", type=int, default=1,
                   help="tensor-parallel way")
    p.add_argument("--optimizer", default="sgd",
                   choices=["sgd", "adam", "adamw", "lamb"])
    p.add_argument("--bf16", action="store_true")
    args = p.parse_args()

    import numpy as onp
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    ndev = jax.device_count()
    dp = args.dp or max(ndev // args.mp, 1)
    mesh = parallel.make_mesh({"dp": dp, "mp": args.mp})
    print(f"mesh: dp={dp} x mp={args.mp} over {ndev} device(s)")

    mx.random.seed(0)
    net = getattr(vision, f"resnet{args.depth}_v1")(classes=args.classes)
    net.initialize(mx.init.Xavier())
    trainer = parallel.SPMDTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        optimizer=args.optimizer,
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9}
        if args.optimizer == "sgd" else {"learning_rate": 1e-3},
        mesh=mesh,
        compute_dtype="bfloat16" if args.bf16 else None)

    rs = onp.random.RandomState(0)
    x = nd.array(rs.rand(args.batch, 3, args.image_size,
                         args.image_size).astype("f"))
    y = nd.array(rs.randint(0, args.classes, args.batch).astype("f"))
    loss = trainer.step(x, y)  # compile
    loss.wait_to_read()
    t0 = time.perf_counter()
    for i in range(args.steps):
        loss = trainer.step(x, y)
    loss.wait_to_read()
    dt = time.perf_counter() - t0
    print(f"loss={float(loss.asscalar()):.4f}  "
          f"{args.batch * args.steps / dt:.1f} img/s")


if __name__ == "__main__":
    main()
