"""Accuracy-parity proxy on REAL data (VERDICT r4 item 9).

This zero-egress environment cannot download CIFAR/ImageNet, but
scikit-learn ships the UCI handwritten-digits dataset (1797 8x8 images,
10 classes) inside the package. Published-comparable baselines on the
standard split: sklearn's own classifier example reports ~97% (SVM,
https://scikit-learn.org/stable/auto_examples/classification/
plot_digits_classification.html); small CNNs reach 98-99%.

This script trains a gluon CNN end to end through the full framework
stack (NDArrayIter -> HybridBlock -> autograd -> Trainer/SGD) and
reports test accuracy. Passing bar: >= 0.97 — matching the published
classical baseline through OUR training loop.

  python examples/train_digits_accuracy.py            # ~2 min on CPU
  python examples/train_digits_accuracy.py --json ACCURACY_r05.json
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--json", default=None,
                   help="write the accuracy artifact here")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    import numpy as onp
    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    # mx.random.seed drives the device PRNG; NDArrayIter's shuffle
    # rides numpy's global RNG — seed it too for a reproducible run
    onp.random.seed(args.seed)
    digits = load_digits()
    X = (digits.images.astype("float32") / 16.0)[:, None, :, :]  # NCHW
    y = digits.target.astype("float32")
    # the canonical evaluation split (sklearn example: 50/50
    # train/test, shuffle with fixed seed)
    Xtr, Xte, ytr, yte = train_test_split(
        X, y, test_size=0.5, random_state=args.seed, shuffle=True)

    mx.random.seed(args.seed)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(32, 3, padding=1, activation="relu"),
            nn.Conv2D(32, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Flatten(),
            nn.Dense(128, activation="relu"),
            nn.Dropout(0.3),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})

    train_iter = mx.io.NDArrayIter(nd.array(Xtr), nd.array(ytr),
                                   batch_size=args.batch, shuffle=True)
    t0 = time.perf_counter()
    for epoch in range(args.epochs):
        train_iter.reset()
        total = correct = 0
        for batch in train_iter:
            xb, yb = batch.data[0], batch.label[0]
            with autograd.record():
                out = net(xb)
                l = loss_fn(out, yb).mean()
            l.backward()
            trainer.step(1)
            pred = out.asnumpy().argmax(1)
            correct += int((pred == yb.asnumpy()).sum())
            total += xb.shape[0]
        if (epoch + 1) % 10 == 0:
            print(f"epoch {epoch + 1}: train acc "
                  f"{correct / max(total, 1):.4f}")
    train_s = time.perf_counter() - t0

    with autograd.pause(train_mode=False):
        logits = net(nd.array(Xte)).asnumpy()
    acc = float((logits.argmax(1) == yte).mean())
    print(f"test accuracy: {acc:.4f} on {len(yte)} held-out digits "
          f"(published classical baseline ~0.97) — trained in "
          f"{train_s:.1f}s")
    payload = {
        "metric": "digits_test_accuracy", "value": round(acc, 4),
        "unit": "top1", "vs_baseline": round(acc / 0.97, 3),
        "extra": {"dataset": "sklearn load_digits (UCI, 1797x8x8)",
                  "split": "50/50 random_state=%d" % args.seed,
                  "published_baseline": 0.97,
                  "epochs": args.epochs, "train_seconds": round(train_s, 1),
                  "note": "zero-egress proxy for VERDICT item 9: real "
                          "data through the full gluon training stack"}}
    print(json.dumps(payload))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(payload, f)
    return acc


if __name__ == "__main__":
    raise SystemExit(0 if main() >= 0.97 else 1)
