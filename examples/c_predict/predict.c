/* Deploy example: classify one sample from a plain C program.
 *
 * Loads the checkpoint exported by export_model.py through the flat C
 * ABI (include/mxnet_tpu/c_api.h + libmxnet_c.so) — no Python source in
 * sight; the library attaches to an embedded interpreter internally.
 *
 * Build + run (from this directory):
 *   python export_model.py
 *   make -C ../../native c_api
 *   gcc predict.c -o predict -I../../include \
 *       ../../mxnet_tpu/_native/libmxnet_c.so \
 *       -Wl,-rpath,$PWD/../../mxnet_tpu/_native
 *   PYTHONPATH=../.. JAX_PLATFORMS=cpu ./predict
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxnet_tpu/c_api.h"

static char* read_file(const char* path, size_t* size) {
  FILE* f = fopen(path, "rb");
  if (!f) { fprintf(stderr, "cannot open %s\n", path); exit(1); }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = malloc(n + 1);
  if (fread(buf, 1, n, f) != (size_t)n) exit(1);
  buf[n] = 0;
  fclose(f);
  *size = (size_t)n;
  return buf;
}

int main(void) {
  size_t json_size, param_size;
  char* sym_json = read_file("mlp-symbol.json", &json_size);
  char* params = read_file("mlp-0000.params", &param_size);

  const char* input_keys[1] = {"data"};
  uint32_t indptr[2] = {0, 2};
  int64_t shape[2] = {1, 16};
  PredictorHandle pred = NULL;
  if (MXPredCreate(sym_json, params, param_size, 1, 0, 1, input_keys,
                   indptr, shape, &pred) != 0) {
    fprintf(stderr, "MXPredCreate: %s\n", MXGetLastError());
    return 1;
  }

  float sample[16];
  FILE* f = fopen("sample.txt", "r");
  if (!f) { fprintf(stderr, "run export_model.py first\n"); return 1; }
  for (int i = 0; i < 16; ++i)
    if (fscanf(f, "%f", &sample[i]) != 1) return 1;
  fclose(f);

  if (MXPredSetInput(pred, "data", sample, 16) != 0 ||
      MXPredForward(pred) != 0) {
    fprintf(stderr, "forward: %s\n", MXGetLastError());
    return 1;
  }
  int ndim = 0;
  int64_t oshape[MX_MAX_DIM];
  if (MXPredGetOutputShape(pred, 0, &ndim, oshape) != 0 ||
      ndim != 2 || oshape[0] != 1 || oshape[1] != 2) {
    fprintf(stderr, "unexpected output shape (ndim=%d)\n", ndim);
    return 1;
  }
  float probs[2];
  if (MXPredGetOutput(pred, 0, probs, 2) != 0) {
    fprintf(stderr, "get output: %s\n", MXGetLastError());
    return 1;
  }
  printf("C probabilities: [%f, %f] -> class %d\n", probs[0], probs[1],
         probs[1] > probs[0] ? 1 : 0);
  MXPredFree(pred);
  free(sym_json);
  free(params);
  return 0;
}
