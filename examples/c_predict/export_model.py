"""Train a small classifier and export it for the C predict API.

Produces mlp-symbol.json + mlp-0000.params (reference checkpoint format,
arg:/aux: tags) that predict.c loads through libmxnet_c.so — the deploy
flow of the reference's example/image-classification/predict-cpp, rebuilt
on this runtime.

Run: python export_model.py   (writes into this directory)
"""
import os

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.module import Module

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    rng = onp.random.RandomState(0)
    X = rng.rand(512, 16).astype("f")
    y = (X[:, :8].sum(1) > X[:, 8:].sum(1)).astype("f")

    data = sym.Variable("data")
    net = sym.FullyConnected(data, name="fc1", num_hidden=32)
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, name="fc2", num_hidden=2)
    out = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax")

    it = NDArrayIter(X, y, batch_size=64, label_name="softmax_label")
    mod = Module(out)
    mod.fit(it, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier())
    score = mod.score(it, "acc")
    print("train accuracy:", score)
    mod.save_checkpoint(os.path.join(HERE, "mlp"), 0)
    # one sample for predict.c to classify
    onp.savetxt(os.path.join(HERE, "sample.txt"), X[:1], fmt="%.6f")
    pred = mod.predict(it).asnumpy()[0]
    print("python probabilities for sample 0:", pred)


if __name__ == "__main__":
    main()
