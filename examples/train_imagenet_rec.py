"""The north-star configuration end to end: ImageNet-style training from
a RecordIO file — native JPEG decode + augment (ImageRecordIter) feeding
the compiled SPMD training step (reference:
example/image-classification/train_imagenet.py, whose data leg is
ImageRecordIter over .rec shards and whose compute leg is ResNet-50).

With no --rec argument a synthetic .rec is written first (JPEG-encoded
random images), so the script runs anywhere:

  python examples/train_imagenet_rec.py --images 256 --batch 32 \
      --image-size 64 --depth 18 --steps 6
  # real data, one TPU chip, bf16:
  python examples/train_imagenet_rec.py --rec train.rec --bf16 \
      --batch 256 --depth 50 --image-size 224
"""
import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def synth_rec(path, n, side, classes, seed=0):
    """JPEG-encode `n` random images into an indexed .rec."""
    from io import BytesIO

    import numpy as onp
    from PIL import Image

    from mxnet_tpu import recordio

    rng = onp.random.RandomState(seed)
    w = recordio.MXIndexedRecordIO(path + ".idx", path, "w")
    blobs = []
    for _ in range(min(n, 64)):  # distinct decode work, bounded gen time
        img = Image.fromarray(rng.randint(0, 255, (side, side, 3), "uint8"))
        buf = BytesIO()
        img.save(buf, format="JPEG", quality=90)
        blobs.append(buf.getvalue())
    for i in range(n):
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % classes), i, 0),
            blobs[i % len(blobs)]))
    w.close()
    return path


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rec", default=None, help=".rec path (synthetic if unset)")
    p.add_argument("--images", type=int, default=256,
                   help="synthetic dataset size")
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--classes", type=int, default=100)
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--depth", type=int, default=18)
    p.add_argument("--dp", type=int, default=0)
    p.add_argument("--threads", type=int, default=os.cpu_count() or 2)
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--stem-s2d", action="store_true",
                   help="space-to-depth stem (224-class of sizes)")
    p.add_argument("--overlap-report", action="store_true",
                   help="measure data-fed vs synthetic-batch rates and "
                        "print an overlap-efficiency JSON line")
    args = p.parse_args()

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import io as mxio, nd, gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    rec = args.rec
    if rec is None:
        rec = os.path.join(tempfile.mkdtemp(prefix="imagenet_rec_"),
                           "train.rec")
        stored = max(args.image_size + args.image_size // 8, 32)
        print(f"writing synthetic {args.images}-image .rec "
              f"({stored}px stored, {args.image_size}px trained) ...")
        synth_rec(rec, args.images, stored, args.classes)

    it = mxio.ImageRecordIter(
        rec, data_shape=(3, args.image_size, args.image_size),
        batch_size=args.batch, path_imgidx=rec + ".idx", shuffle=True,
        rand_crop=True, rand_mirror=True,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.1, std_b=57.4,
        preprocess_threads=args.threads, prefetch_buffer=4)

    ndev = jax.device_count()
    dp = args.dp or ndev
    mesh = parallel.make_mesh({"dp": dp})
    print(f"mesh: dp={dp} over {ndev} device(s)")

    mx.random.seed(0)
    net = getattr(vision, f"resnet{args.depth}_v1")(
        classes=args.classes, stem_s2d=args.stem_s2d)
    net.initialize(mx.init.Xavier())
    trainer = parallel.SPMDTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
        mesh=mesh, compute_dtype="bfloat16" if args.bf16 else None)

    feed = mxio.DevicePrefetchIter(it) if args.overlap_report else it

    syn_rate = None
    if args.overlap_report:
        # synthetic ceiling FIRST, while the input pipeline is idle —
        # measuring it after the fed loop would time against still-busy
        # decode/prefetch threads and overstate overlap efficiency
        import numpy as onp

        rs = onp.random.RandomState(0)
        xs = nd.array(rs.rand(args.batch, 3, args.image_size,
                              args.image_size).astype("f"))
        ys = nd.array(rs.randint(0, args.classes, args.batch).astype("f"))
        l2 = trainer.step(xs, ys)
        l2.wait_to_read()  # compile
        n_syn = max(args.steps, 4)
        t1 = time.perf_counter()
        for _ in range(n_syn):
            l2 = trainer.step(xs, ys)
        l2.wait_to_read()
        syn_rate = args.batch * n_syn / (time.perf_counter() - t1)

    # NCHW batches from the decode pipeline; the model runs its layout
    step = imgs = 0
    loss = None
    t0 = None
    for _epoch in range(args.epochs):
        for batch in feed:
            if batch.data[0].shape[0] != args.batch:
                continue  # tail batch: keep ONE compiled shape
            loss = trainer.step(batch.data[0], batch.label[0])
            step += 1
            if step == 1:  # compile step: start the clock after it
                loss.wait_to_read()
                t0 = time.perf_counter()
            else:
                imgs += args.batch
            if args.steps and step >= args.steps + 1:
                break
        feed.reset()
        if args.steps and step >= args.steps + 1:
            break
    if loss is None or t0 is None:
        raise SystemExit(
            f"no full batch of {args.batch} was produced — the dataset "
            f"has fewer than 2x batch_size usable images; lower --batch "
            f"or raise --images")
    loss.wait_to_read()
    dt = time.perf_counter() - t0
    fed_rate = imgs / dt
    print(f"steps={step} loss={float(loss.asscalar()):.4f} "
          f"pipeline {fed_rate:.1f} img/s (decode+augment+train)")
    if args.overlap_report:
        # fed/synthetic ratio quantifies how completely decode+H2D hide
        # behind the compiled step (VERDICT r4 weak #3: 'within ~10% of
        # synthetic' is the target)
        import json as _json

        print(_json.dumps({
            "metric": "data_fed_train_imgs_per_sec",
            "value": round(fed_rate, 2), "unit": "img/s",
            "vs_baseline": 0.0,
            "extra": {"synthetic_step_imgs_per_sec": round(syn_rate, 2),
                      "overlap_efficiency_pct": round(
                          100.0 * fed_rate / syn_rate, 1),
                      "batch": args.batch, "depth": args.depth,
                      "image_size": args.image_size,
                      "threads": args.threads}}))


if __name__ == "__main__":
    main()
