"""Matrix-factorization recommender (reference:
example/recommenders/demo1-MF.ipynb + example/sparse/matrix_factorization
— the classic two-Embedding dot-product model, trained here with the
gluon API on synthetic ratings).

  python examples/train_recommender_mf.py --users 200 --items 120
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--users", type=int, default=200)
    p.add_argument("--items", type=int, default=120)
    p.add_argument("--rank", type=int, default=16)
    p.add_argument("--ratings", type=int, default=4000)
    p.add_argument("--epochs", type=int, default=15)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    onp.random.seed(args.seed)
    mx.random.seed(args.seed)
    # synthetic low-rank ground truth + noise
    true_u = onp.random.randn(args.users, 4).astype("f")
    true_i = onp.random.randn(args.items, 4).astype("f")
    u_idx = onp.random.randint(0, args.users, args.ratings)
    i_idx = onp.random.randint(0, args.items, args.ratings)
    ratings = (true_u[u_idx] * true_i[i_idx]).sum(1) + \
        0.1 * onp.random.randn(args.ratings).astype("f")

    class MFBlock(gluon.HybridBlock):
        def __init__(self, n_users, n_items, rank):
            super().__init__()
            self.user_emb = nn.Embedding(n_users, rank)
            self.item_emb = nn.Embedding(n_items, rank)

        def hybrid_forward(self, F, users, items):
            u = self.user_emb(users)
            i = self.item_emb(items)
            return (u * i).sum(axis=1)

    net = MFBlock(args.users, args.items, args.rank)
    net.initialize(mx.init.Normal(0.1))
    net.hybridize()
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.02})

    n = args.ratings
    t0 = time.perf_counter()
    first = last = None
    for epoch in range(args.epochs):
        perm = onp.random.permutation(n)
        total = 0.0
        for s in range(0, n - args.batch + 1, args.batch):
            sel = perm[s:s + args.batch]
            bu = nd.array(u_idx[sel].astype("f"))
            bi = nd.array(i_idx[sel].astype("f"))
            br = nd.array(ratings[sel])
            with autograd.record():
                pred = net(bu, bi)
                l = loss_fn(pred, br).mean()
            l.backward()
            trainer.step(1)
            total += float(l.asscalar())
        mse = 2 * total / max(1, (n // args.batch))  # L2Loss = 1/2 MSE
        if first is None:
            first = mse
        last = mse
    dt = time.perf_counter() - t0
    print(f"MF {args.users}x{args.items} rank={args.rank}: train MSE "
          f"{first:.4f} -> {last:.4f} in {dt:.1f}s")
    assert last < first * 0.25, "matrix factorization did not converge"
    return last


if __name__ == "__main__":
    main()
