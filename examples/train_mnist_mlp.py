"""MLP classifier with the Module API (reference:
example/image-classification/train_mnist.py).

Synthetic data stands in for MNIST (no dataset egress in this
environment); swap in mx.gluon.data.vision.MNIST for the real thing.

  python examples/train_mnist_mlp.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.module import Module


def mlp_symbol(num_classes=10):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, name="fc1", num_hidden=128,
                             weight=sym.Variable("fc1_weight"),
                             bias=sym.Variable("fc1_bias"))
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, name="fc2", num_hidden=64,
                             weight=sym.Variable("fc2_weight"),
                             bias=sym.Variable("fc2_bias"))
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, name="fc3", num_hidden=num_classes,
                             weight=sym.Variable("fc3_weight"),
                             bias=sym.Variable("fc3_bias"))
    return sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                             name="softmax")


def main():
    rs = onp.random.RandomState(0)
    X = rs.rand(2048, 784).astype("f")
    w = rs.randn(784, 10).astype("f")
    y = (X @ w).argmax(1).astype("f")
    train = NDArrayIter(X[:1792], y[:1792], batch_size=128,
                        shuffle=True, label_name="softmax_label")
    val = NDArrayIter(X[1792:], y[1792:], batch_size=128,
                      label_name="softmax_label")
    mod = Module(mlp_symbol())
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": 0.3, "momentum": 0.9},
            num_epoch=8,
            batch_end_callback=mx.callback.Speedometer(128, 10))
    score = mod.score(val, "acc")
    print("validation accuracy:", score)


if __name__ == "__main__":
    main()
