"""Toy SSD-style detector: the MultiBox pipeline end to end.

Reference workflow: example/ssd (MultiBoxPrior → MultiBoxTarget →
SmoothL1 + softmax losses → MultiBoxDetection at inference), shrunk to a
synthetic dataset of colored squares so it runs in seconds on CPU/TPU.

Run: JAX_PLATFORMS=cpu python examples/train_ssd_toy.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn

IMG = 64
CLASSES = 2  # square / circle-ish blob


def synth_batch(rng, batch):
    """Images with ONE bright square each; label = (cls, x0, y0, x1, y1)."""
    x = rng.rand(batch, 3, IMG, IMG).astype("f") * 0.1
    labels = onp.zeros((batch, 1, 5), "f")
    for i in range(batch):
        cls = rng.randint(0, CLASSES)
        w = rng.randint(12, 28)
        x0 = rng.randint(0, IMG - w)
        y0 = rng.randint(0, IMG - w)
        x[i, cls, y0:y0 + w, x0:x0 + w] = 1.0
        labels[i, 0] = [cls, x0 / IMG, y0 / IMG, (x0 + w) / IMG,
                        (y0 + w) / IMG]
    return nd.array(x), nd.array(labels)


class ToySSD(gluon.Block):
    """Imperative Block: the heads use concrete shapes for reshaping
    (hybridize-safe variants would use reshape((0, -1, ...)) codes)."""
    def __init__(self, num_anchors, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.backbone = nn.HybridSequential()
            for ch in (16, 32, 64):
                self.backbone.add(
                    nn.Conv2D(ch, 3, strides=2, padding=1,
                              activation="relu"))
            self.cls_head = nn.Conv2D(num_anchors * (CLASSES + 1), 3,
                                      padding=1)
            self.loc_head = nn.Conv2D(num_anchors * 4, 3, padding=1)

    def forward(self, x):
        feat = self.backbone(x)  # (B, 64, 8, 8)
        cls = self.cls_head(feat)  # (B, A*(C+1), 8, 8)
        loc = self.loc_head(feat)  # (B, A*4, 8, 8)
        B = cls.shape[0]
        cls = cls.transpose((0, 2, 3, 1)).reshape(B, -1, CLASSES + 1)
        loc = loc.transpose((0, 2, 3, 1)).reshape(B, -1)
        return feat, cls, loc


def main():
    mx.random.seed(0)
    rng = onp.random.RandomState(0)
    sizes = [0.2, 0.4]
    ratios = [1.0, 1.5]
    num_anchors = len(sizes) + len(ratios) - 1
    net = ToySSD(num_anchors)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    anchors = None
    for step in range(120):
        x, labels = synth_batch(rng, 16)
        with autograd.record():
            feat, cls_preds, loc_preds = net(x)
            if anchors is None:
                anchors = nd.contrib.MultiBoxPrior(
                    feat, sizes=sizes, ratios=ratios)
            loc_t, loc_mask, cls_t = nd.contrib.MultiBoxTarget(
                anchors, labels, cls_preds.transpose((0, 2, 1)))
            cls_loss = ce(cls_preds.reshape(-1, CLASSES + 1),
                          cls_t.reshape(-1))
            loc_loss = nd.mean(nd.smooth_l1(
                (loc_preds - loc_t) * loc_mask, scalar=1.0))
            loss = nd.mean(cls_loss) + loc_loss
        loss.backward()
        trainer.step(16)
        if step % 20 == 0:
            print(f"step {step}: loss={float(loss.asscalar()):.4f}")

    # inference: decode + NMS
    x, labels = synth_batch(rng, 4)
    feat, cls_preds, loc_preds = net(x)
    probs = nd.softmax(cls_preds, axis=-1).transpose((0, 2, 1))
    dets = nd.contrib.MultiBoxDetection(probs, loc_preds, anchors,
                                        threshold=0.1)
    kept = dets.asnumpy()[0]
    kept = kept[kept[:, 0] >= 0]
    print(f"detections for image 0 (gt cls {int(labels.asnumpy()[0,0,0])}"
          f" box {labels.asnumpy()[0,0,1:].round(2)}):")
    for d in kept[:3]:
        print(f"  cls={int(d[0])} score={d[1]:.2f} box={d[2:].round(2)}")
    final = float(loss.asscalar())
    print("done; final loss", round(final, 4))
    assert final < 2.0, "training diverged"


if __name__ == "__main__":
    main()
