"""Word-level LM with bucketed sequences + legacy RNN cells
(reference: example/rnn/bucketing/lstm_bucketing.py).

  python examples/train_lm_bucketing.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, rnn, sym
from mxnet_tpu.module import BucketingModule


def main():
    rs = onp.random.RandomState(0)
    vocab_size, hidden = 50, 32
    sentences = [list(rs.randint(1, vocab_size,
                                 rs.randint(3, 12)).astype(int))
                 for _ in range(256)]
    buckets = [4, 8, 12]
    it = rnn.BucketSentenceIter(sentences, batch_size=16,
                                buckets=buckets, invalid_label=0)

    cell = rnn.LSTMCell(hidden, prefix="lstm_")

    batch_size = 16

    def sym_gen(seq_len):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        embed = sym.embedding(data, sym.Variable("embed_weight"),
                              input_dim=vocab_size, output_dim=hidden,
                              name="embed")
        # static zero initial states keep shape inference closed
        begin = [sym.zeros((batch_size, hidden)),
                 sym.zeros((batch_size, hidden))]
        outputs, _ = cell.unroll(seq_len, embed, begin_state=begin,
                                 merge_outputs=True)
        pred = sym.reshape(outputs, shape=(-1, hidden))
        pred = sym.FullyConnected(pred, num_hidden=vocab_size,
                                  weight=sym.Variable("cls_weight"),
                                  bias=sym.Variable("cls_bias"),
                                  name="cls")
        label = sym.reshape(label, shape=(-1,))
        out = sym.SoftmaxOutput(pred, label, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = BucketingModule(sym_gen,
                          default_bucket_key=it.default_bucket_key)
    mod.fit(it, optimizer="adam",
            optimizer_params={"learning_rate": 0.01}, num_epoch=3,
            eval_metric="loss")
    print("done; perplexity tracked via eval_metric")


if __name__ == "__main__":
    main()
