"""Module-API data parallelism: the reference's
``Module(context=[mx.gpu(0), mx.gpu(1), ...])`` flow on a TPU device
mesh (reference: example/image-classification with --gpus, backed by
DataParallelExecutorGroup — here ONE batch-sharded XLA computation).

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/train_module_dp.py --ndev 8
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ndev", type=int, default=0,
                   help="contexts to bind (default: all devices)")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--epochs", type=int, default=6)
    args = p.parse_args()

    import jax
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import sym, io
    from mxnet_tpu.module import Module

    ndev = args.ndev or jax.device_count()
    ctxs = [mx.cpu(i) if jax.devices()[0].platform == "cpu" else mx.tpu(i)
            for i in range(ndev)]
    print(f"binding over {ndev} context(s): {ctxs}")

    rs = onp.random.RandomState(0)
    X = rs.randn(1024, 16).astype("f")
    y = (X[:, :8].sum(1) > X[:, 8:].sum(1)).astype("f")

    data = sym.Variable("data")
    net = sym.FullyConnected(data, name="fc1", num_hidden=64)
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, name="fc2", num_hidden=2)
    out = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax")

    mod = Module(out, context=ctxs if ndev > 1 else ctxs[0])
    train = io.NDArrayIter(X, y, batch_size=args.batch, shuffle=True)
    mod.fit(train, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(args.batch, 8))
    score = mod.score(io.NDArrayIter(X, y, batch_size=args.batch), "acc")
    print(f"final accuracy over {ndev} device(s): {dict(score)}")


if __name__ == "__main__":
    main()
