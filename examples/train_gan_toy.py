"""Toy GAN: generator vs discriminator on a 2-D Gaussian ring
(reference: example/gluon/dcgan.py's training pattern — two Trainers,
detached generator samples for the D step, adversarial losses — at
smoke scale).

  python examples/train_gan_toy.py --steps 200
  python examples/train_gan_toy.py --cpu   # skip the TPU tunnel
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def real_batch(rng, n):
    import numpy as onp

    theta = rng.rand(n) * 2 * onp.pi
    pts = onp.stack([2.0 * onp.cos(theta), 2.0 * onp.sin(theta)], 1)
    return (pts + rng.randn(n, 2) * 0.05).astype("f")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--latent", type=int, default=8)
    p.add_argument("--cpu", action="store_true",
                   help="force the host-CPU platform (use when the TPU "
                        "tunnel is absent or unhealthy — the env-var "
                        "escape only works if set before python starts)")
    args = p.parse_args()

    if args.cpu:
        from _cpu_platform import force_cpu_platform

        force_cpu_platform()

    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    mx.random.seed(0)
    G = gluon.nn.HybridSequential()
    G.add(gluon.nn.Dense(32, activation="relu"),
          gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(2))
    D = gluon.nn.HybridSequential()
    D.add(gluon.nn.Dense(32, activation="relu"),
          gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(1))
    for net in (G, D):
        net.initialize(mx.init.Xavier())
        net.hybridize()
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    gt = gluon.Trainer(G.collect_params(), "adam",
                       {"learning_rate": 2e-3, "beta1": 0.5})
    dt = gluon.Trainer(D.collect_params(), "adam",
                       {"learning_rate": 2e-3, "beta1": 0.5})
    rng = onp.random.RandomState(0)
    ones = nd.ones((args.batch,))
    zeros = nd.zeros((args.batch,))
    dl = gl = None
    for step in range(args.steps):
        z = nd.array(rng.randn(args.batch, args.latent).astype("f"))
        real = nd.array(real_batch(rng, args.batch))
        # D step: real -> 1, detached fake -> 0
        with autograd.record():
            fake = G(z).detach()
            dl = (loss_fn(D(real), ones) + loss_fn(D(fake), zeros)).mean()
        dl.backward()
        dt.step(args.batch)
        # G step: fool D
        with autograd.record():
            gl = loss_fn(D(G(z)), ones).mean()
        gl.backward()
        gt.step(args.batch)
        if step % 50 == 0:
            print(f"step {step:4d}  d_loss={float(dl.asscalar()):.3f}  "
                  f"g_loss={float(gl.asscalar()):.3f}")
    # generated points should land near the radius-2 ring
    z = nd.array(rng.randn(512, args.latent).astype("f"))
    pts = G(z).asnumpy()
    radii = onp.sqrt((pts ** 2).sum(1))
    dtxt = f"{float(dl.asscalar()):.3f}" if dl is not None else "n/a"
    print(f"final: mean radius {radii.mean():.3f} (target 2.0), "
          f"d_loss={dtxt}")
    return radii.mean()


if __name__ == "__main__":
    main()
