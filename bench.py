"""Benchmark: ResNet-50 training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: reference MXNet ResNet-50 training, fp32 batch 128 on 1x V100 =
363.69 img/s (BASELINE.md, docs perf.md:243-254). The full training step
(forward, backward, SGD+momentum update, BN stats) is ONE donated XLA
executable built by mxnet_tpu.parallel.SPMDTrainer over a 1-device mesh.

Robustness: the axon TPU tunnel admits one process at a time and its
backend init can hang or fail transiently (round-1 BENCH died at backend
setup). The parent process therefore runs the measurement in a CHILD
subprocess with a per-attempt timeout and retries with backoff; if the TPU
never comes up it falls back to a small CPU measurement so a parsed number
always exists (metric name says which platform produced it).

Env knobs:
  BENCH_BATCH   (default 128; halved on OOM, progress carried across
                retries via BENCH_STATE)
  BENCH_SMOKE=1 tiny-shape CPU smoke for plumbing checks
  BENCH_CHILD   internal: set by the parent to 'axon' or 'cpu'
  BENCH_STATE   internal: file where the child records the last batch
                size it attempted, so a retry resumes the OOM descent
  BENCH_ATTEMPT_TIMEOUT hard wall for a TPU attempt that has started
                compiling/running (default 3600; the tunnel-dial phases
                are capped at 1800 regardless)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_IMGS_PER_SEC = 363.69  # reference fp32 training, 1xV100
SCORE_V100_FP32 = 1233.15  # scoring, fp32 b128 (perf.md:187-197)
SCORE_V100_FP16 = 2355.04  # scoring, fp16 b128 (perf.md:199-215)
# the reference publishes no fp16 TRAINING number; its fp16/fp32 scoring
# ratio (perf.md:187-215) applied to the fp32 training baseline is the
# fairest half-precision comparison point
BASELINE_FP16_EST = BASELINE_IMGS_PER_SEC * SCORE_V100_FP16 / SCORE_V100_FP32
# ResNet-50 fwd = 4.089 GFLOP/img at 224x224 (2 FLOPs/MAC); training
# fwd+bwd ~ 3x fwd
TRAIN_GFLOPS_PER_IMG = 3 * 4.089
# bf16 MXU peak per chip by device_kind (TFLOP/s)
PEAK_TFLOPS = {"TPU v4": 275, "TPU v5": 459, "TPU v5p": 459,
               "TPU v5 lite": 197, "TPU v5e": 197,
               "TPU v6 lite": 918, "TPU v6e": 918}
SMOKE = os.environ.get("BENCH_SMOKE") == "1"
CHILD = os.environ.get("BENCH_CHILD")


from _cpu_platform import force_cpu_platform


# ---------------------------------------------------------------- child ---

LAYOUT = os.environ.get("BENCH_LAYOUT", "NHWC")  # NHWC = TPU-preferred


def build_trainer(mesh, classes=1000, dtype=None, layout=None):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu import parallel

    mx.random.seed(0)
    # MLPerf-style space-to-depth stem: bit-equivalent to the 7x7/2 conv
    # (tests/test_s2d_stem.py) but MXU-friendly; BENCH_STEM_S2D=0 reverts
    net = vision.resnet50_v1(
        classes=classes, layout=layout or LAYOUT,
        stem_s2d=os.environ.get("BENCH_STEM_S2D", "1") == "1")
    net.initialize(mx.init.Xavier())
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    return parallel.SPMDTrainer(
        net, loss, optimizer="sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
        mesh=mesh, compute_dtype=dtype)


def setup_train(batch, image_size, classes, dtype=None):
    """One-chip trainer + synthetic batch — shared by the timed run and
    the profile capture so both measure the identical program."""
    import jax
    import numpy as onp

    from mxnet_tpu import nd, parallel

    mesh = parallel.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = build_trainer(mesh, classes, dtype=dtype)
    rng = onp.random.RandomState(0)
    shape = ((batch, image_size, image_size, 3) if LAYOUT == "NHWC"
             else (batch, 3, image_size, image_size))
    x = nd.array(rng.rand(*shape).astype("f"))
    y = nd.array(rng.randint(0, classes, batch).astype("f"))
    return trainer, x, y


def run(batch, image_size, classes, warmup=2, iters=8, dtype=None):
    import jax

    trainer, x, y = setup_train(batch, image_size, classes, dtype)
    # Sync via device_get of the scalar loss, NOT wait_to_read: on the
    # tunneled axon platform block_until_ready returns before the device
    # finishes, so only a host readback is a faithful barrier (verified:
    # chained 8192^3 matmuls "complete" in 0.1ms under block_until_ready
    # but meter 131-151 TF/s — 66-77% of v5e peak — under device_get).
    for _ in range(warmup):
        lval = trainer.step(x, y)
    _ = jax.device_get(lval.data)
    t0 = time.perf_counter()
    for _ in range(iters):
        lval = trainer.step(x, y)
    loss_val = float(jax.device_get(lval.data))
    dt = time.perf_counter() - t0
    return batch * iters / dt, loss_val


def build_scoring(image_size=224):
    """Build the scoring net ONCE (off-tunnel) and stage params on the
    device; run_scoring reuses it across dtypes."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.model_zoo import vision
    import numpy as onp

    mx.random.seed(0)
    net = vision.resnet50_v1(layout=LAYOUT)
    cpu = jax.devices("cpu")[0]
    shape = ((1, image_size, image_size, 3) if LAYOUT == "NHWC"
             else (1, 3, image_size, image_size))
    with jax.default_device(cpu):  # build off-tunnel
        net.initialize(mx.init.Xavier())
        with autograd.pause(train_mode=False):
            net.forward(mx.nd.array(onp.zeros(shape, "f")))
    params = [p for _, p in sorted(net.collect_params().items())]
    pnds = [p._ndarray for p in params]
    dev = jax.devices()[0]
    vals = [jax.device_put(p._ndarray.data, dev) for p in params]
    return net, pnds, vals, shape


def run_scoring(batch, built, dtype=None, iters=30):
    """Inference ("scoring") throughput: the whole measurement is ONE
    jitted fori_loop whose carry threads an epsilon of each output back
    into the input, so no per-iteration dispatch crosses the tunnel and
    XLA cannot collapse identical iterations. Reference comparison:
    perf.md:187-215 V100 scoring table."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mxnet_tpu import autograd
    from mxnet_tpu.ndarray import NDArray
    import numpy as onp

    net, pnds, vals, shape = built
    dev = jax.devices()[0]
    cdtype = jnp.dtype(dtype) if dtype else None

    def fwd(pv, x):
        saved = [p._data for p in pnds]
        try:
            for p, v in zip(pnds, pv):
                if cdtype is not None and \
                        jnp.issubdtype(v.dtype, jnp.floating):
                    v = v.astype(cdtype)
                p._data = v
            xin = x.astype(cdtype) if cdtype is not None else x
            with autograd.pause(train_mode=False):
                out = net.forward(NDArray(xin))
            return out.data.astype(jnp.float32)
        finally:
            for p, v in zip(pnds, saved):
                p._data = v

    def loop(pv, x):
        def body(i, carry):
            xc, acc = carry
            o = fwd(pv, xc)
            s = jnp.sum(o)
            return xc + (1e-30 * s).astype(xc.dtype), acc + s

        return lax.fori_loop(0, iters, body, (x, jnp.float32(0)))

    rng = onp.random.RandomState(0)
    bshape = (batch,) + shape[1:]
    x = jax.device_put(jnp.asarray(rng.rand(*bshape).astype("f")), dev)
    jloop = jax.jit(loop)
    _, acc = jloop(vals, x)  # compile + run once
    _ = jax.device_get(acc)
    t0 = time.perf_counter()
    _, acc = jloop(vals, x)
    _ = jax.device_get(acc)
    dt = time.perf_counter() - t0
    return batch * iters / dt


def _score_with_descent(batch, built, dtype):
    """OOM-halving like the training phases."""
    while batch >= 16:
        try:
            return run_scoring(batch, built, dtype=dtype), batch
        except RuntimeError as e:
            if "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e):
                batch //= 2
                continue
            raise
    raise RuntimeError("scoring failed at batch>=16")


def mfu_pct(imgs_per_sec):
    """Sustained training FLOP/s as % of the chip's bf16 MXU peak."""
    import jax

    kind = jax.devices()[0].device_kind
    peak = PEAK_TFLOPS.get(kind)
    if peak is None:  # longest-matching-prefix fallback ("TPU v5 lite"...)
        match = max((k for k in PEAK_TFLOPS if kind.startswith(k)),
                    key=len, default=None)
        peak = PEAK_TFLOPS.get(match)
    if not peak:
        return None
    return round(100.0 * imgs_per_sec * TRAIN_GFLOPS_PER_IMG
                 / (peak * 1000.0), 2)


def child_main(platform):
    if platform == "cpu":
        force_cpu_platform()
        imgs, _ = run(batch=8, image_size=64, classes=100, warmup=1, iters=4)
        # different workload (64px/100cls) — not comparable to the V100
        # 224px baseline, so vs_baseline stays 0 like the smoke
        print(json.dumps({
            "metric": "resnet50_train_imgs_per_sec_fp32_cpu_fallback",
            "value": round(imgs, 2), "unit": "img/s", "vs_baseline": 0.0}))
        return
    state = os.environ.get("BENCH_STATE")
    progress = {}  # persisted across retries via the state file

    def checkpoint(phase=None):
        if phase:
            progress["phase"] = phase
        if state:
            try:
                with open(state, "w") as f:
                    f.write(json.dumps(progress))
            except OSError:
                pass

    # phase markers drive the parent's kill policy: it may only
    # terminate a child that has not yet claimed the tunnel ("init") —
    # killing mid-compile is what wedged the tunnel in rounds 3/4
    checkpoint("init")
    import jax

    jax.devices()  # tunnel dial happens HERE, before any compile
    checkpoint("devices")

    def measure(tag, batch, dtype):
        """OOM-halving descent; returns (imgs/s, batch) or raises
        RuntimeError (NOT SystemExit — the bf16 phase's failure must be
        catchable so a measured fp32 result still gets printed)."""
        last_err = None
        while batch >= 16:
            progress.update({"tag": tag, "batch": batch})
            checkpoint("compile")  # a fresh batch size recompiles
            try:
                imgs, _ = run(batch=batch, image_size=224, classes=1000,
                              dtype=dtype)
                checkpoint("run")
                return imgs, batch
            except RuntimeError as e:  # OOM → halve the batch
                last_err = e
                if "RESOURCE_EXHAUSTED" in str(e) or \
                        "Out of memory" in str(e):
                    batch //= 2
                    continue
                raise
        raise RuntimeError(f"bench {tag} failed at batch>=16: {last_err}")

    fp32_batch = int(os.environ.get("BENCH_BATCH", "128"))
    # bf16 halves activation memory — start the descent high: bigger
    # batches keep the MXU fed (the OOM-halving loop finds the ceiling)
    bf16_batch = int(os.environ.get("BENCH_BF16_BATCH", "512"))
    # resume point from a killed attempt: skip straight to its phase,
    # reusing the fp32 result the killed attempt already measured
    resume = {}
    if os.environ.get("BENCH_RESUME"):
        try:
            resume = json.loads(os.environ["BENCH_RESUME"])
        except ValueError:
            pass
    if resume.get("tag") == "fp32":
        fp32_batch = int(resume["batch"])
    elif resume.get("tag") == "bf16":
        bf16_batch = int(resume["batch"])

    if resume.get("fp32_done"):
        imgs32, b32 = resume["fp32_done"]
        progress["fp32_done"] = resume["fp32_done"]
    else:
        imgs32, b32 = measure("fp32", fp32_batch, None)
        progress["fp32_done"] = [imgs32, b32]
        checkpoint()
    extra = {"fp32_imgs_per_sec": round(imgs32, 2), "fp32_batch": b32,
             "fp32_vs_v100_fp32_train": round(
                 imgs32 / BASELINE_IMGS_PER_SEC, 3)}
    m32 = mfu_pct(imgs32)
    if m32 is not None:
        extra["fp32_mfu_pct_of_bf16_peak"] = m32
    try:
        imgs16, b16 = measure("bf16", bf16_batch, "bfloat16")
    except Exception as e:
        print(f"[bench] bf16 phase failed: {e}", file=sys.stderr)
        imgs16 = None
    if imgs16 is not None:
        m16 = mfu_pct(imgs16)
        if m16 is not None:
            extra["bf16_mfu_pct_of_bf16_peak"] = m16
        extra["bf16_vs_v100_fp16_train_est"] = round(
            imgs16 / BASELINE_FP16_EST, 3)
        extra["bf16_speedup_over_fp32"] = round(imgs16 / imgs32, 3)
        result = {
            "metric": f"resnet50_train_imgs_per_sec_bf16_b{b16}",
            "value": round(imgs16, 2), "unit": "img/s",
            "vs_baseline": round(imgs16 / BASELINE_IMGS_PER_SEC, 3),
            "extra": extra}
    else:
        result = {
            "metric": f"resnet50_train_imgs_per_sec_fp32_b{b32}",
            "value": round(imgs32, 2), "unit": "img/s",
            "vs_baseline": round(imgs32 / BASELINE_IMGS_PER_SEC, 3),
            "extra": extra}
    # training results are safe NOW (the parent takes the LAST metric
    # line) — a scoring hang/failure can no longer discard them
    print(json.dumps(result), flush=True)
    checkpoint("scoring")
    # inference scoring vs the reference's V100 table (perf.md:187-215);
    # per-dtype try so an fp32 failure doesn't take bf16 down with it
    try:
        built = build_scoring()
    except Exception as e:
        print(f"[bench] scoring build failed: {e}", file=sys.stderr)
        built = None
    if built is not None:
        for tag, dt_, base, base_name in (
                ("fp32", None, SCORE_V100_FP32, "v100"),
                ("bf16", "bfloat16", SCORE_V100_FP16, "v100_fp16")):
            try:
                sc, sb = _score_with_descent(128, built, dt_)
                extra[f"score_{tag}_imgs_per_sec_b{sb}"] = round(sc, 2)
                extra[f"score_{tag}_vs_{base_name}"] = round(sc / base, 3)
            except Exception as e:
                print(f"[bench] {tag} scoring failed: {e}",
                      file=sys.stderr)
        result["extra"] = extra
        print(json.dumps(result), flush=True)


def smoke_main():
    force_cpu_platform()
    imgs, _ = run(batch=4, image_size=32, classes=10, warmup=1, iters=2)
    print(json.dumps({"metric": "resnet50_train_smoke",
                      "value": round(imgs, 2), "unit": "img/s",
                      "vs_baseline": 0.0}))


def profile_main():
    """BENCH_MODE=profile: capture an XPlane trace of a few training
    steps for the MFU breakdown (the VERDICT's 'profile a step and
    attack the top time sinks' loop). Writes to BENCH_PROFILE_DIR
    (default ./bench_profile) — open in TensorBoard/Perfetto, or read
    the top self-time ops from the .trace.json.gz inside."""
    import jax

    outdir = os.environ.get("BENCH_PROFILE_DIR", "bench_profile")
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    dtype = os.environ.get("BENCH_PROFILE_DTYPE", "bfloat16")
    image_size = int(os.environ.get("BENCH_PROFILE_IMAGE", "224"))
    trainer, x, y = setup_train(batch, image_size, 1000, dtype)
    lval = trainer.step(x, y)  # compile OUTSIDE the trace
    _ = jax.device_get(lval.data)
    with jax.profiler.trace(outdir):
        for _ in range(int(os.environ.get("BENCH_PROFILE_STEPS", "5"))):
            lval = trainer.step(x, y)
        _ = jax.device_get(lval.data)
    # fold the top self-time table straight into the artifact so one
    # command yields the attack-the-sinks breakdown
    top = []
    try:
        from mxnet_tpu.tools import trace_top

        trace = trace_top.find_trace(outdir)
        events = trace_top.device_op_events(trace_top.load_events(trace))
        tot, cnt = trace_top.summarize(events)
        grand = sum(tot.values()) or 1
        top = [{"op": k, "self_ms": round(us / 1e3, 3),
                "pct": round(100.0 * us / grand, 2), "count": cnt[k]}
               for k, us in tot.most_common(12)]
    except Exception as e:  # trace parse must not discard the capture
        print(f"[bench] trace summary failed: {e}", file=sys.stderr)
    print(json.dumps({
        "metric": "profile_trace_written", "value": 1.0, "unit": "trace",
        "vs_baseline": 0.0,
        "extra": {"dir": os.path.abspath(outdir), "batch": batch,
                  "dtype": dtype,
                  "device": jax.devices()[0].device_kind,
                  "top_self_time": top}}))


def rawjax_main():
    """BENCH_MODE=rawjax: a hand-written ResNet-50 bf16 training step in
    bare JAX (no framework) — the platform ceiling for this model+chip.
    Comparing its img/s against the default bench isolates framework
    overhead from XLA/hardware limits."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    import numpy as onp

    batch = int(os.environ.get("BENCH_BATCH", "512"))
    rng = onp.random.RandomState(0)
    cdt = jnp.bfloat16

    # ---- parameters (fp32 masters), NHWC, bottleneck v1 ----
    params = {}

    def conv_p(name, cin, cout, k):
        params[name + ":w"] = jnp.asarray(
            rng.randn(cout, k, k, cin).astype("f") * (2.0 / (k * k * cin)) ** 0.5)

    def bn_p(name, c):
        params[name + ":g"] = jnp.ones((c,), jnp.float32)
        params[name + ":b"] = jnp.zeros((c,), jnp.float32)

    stages = [(64, 256, 3), (128, 512, 4), (256, 1024, 6), (512, 2048, 3)]
    conv_p("stem", 3, 64, 7)
    bn_p("stem", 64)
    cin = 64
    for si, (mid, out, n) in enumerate(stages):
        for bi in range(n):
            pre = f"s{si}b{bi}"
            conv_p(pre + "c1", cin, mid, 1)
            bn_p(pre + "c1", mid)
            conv_p(pre + "c2", mid, mid, 3)
            bn_p(pre + "c2", mid)
            conv_p(pre + "c3", mid, out, 1)
            bn_p(pre + "c3", out)
            if bi == 0:
                conv_p(pre + "ds", cin, out, 1)
                bn_p(pre + "ds", out)
            cin = out
    params["fc:w"] = jnp.asarray(rng.randn(2048, 1000).astype("f") * 0.02)
    params["fc:b"] = jnp.zeros((1000,), jnp.float32)

    def conv(x, w, stride=1):
        return lax.conv_general_dilated(
            x, jnp.transpose(w, (1, 2, 3, 0)).astype(cdt),
            (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def bn_relu(x, g, b, relu=True):
        xf = x.astype(jnp.float32)
        m = jnp.mean(xf, axis=(0, 1, 2))
        v = jnp.var(xf, axis=(0, 1, 2))
        y = (xf - m) * lax.rsqrt(v + 1e-5) * g + b
        if relu:
            y = jnp.maximum(y, 0.0)
        return y.astype(cdt)

    def fwd(p, x, y):
        h = conv(x, p["stem:w"], 2)
        h = bn_relu(h, p["stem:g"], p["stem:b"])
        h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
        for si, (mid, out, n) in enumerate(stages):
            for bi in range(n):
                pre = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                r = h
                h2 = bn_relu(conv(h, p[pre + "c1:w"], stride),
                             p[pre + "c1:g"], p[pre + "c1:b"])
                h2 = bn_relu(conv(h2, p[pre + "c2:w"]),
                             p[pre + "c2:g"], p[pre + "c2:b"])
                h2 = bn_relu(conv(h2, p[pre + "c3:w"]),
                             p[pre + "c3:g"], p[pre + "c3:b"], relu=False)
                if bi == 0:
                    r = bn_relu(conv(r, p[pre + "ds:w"], stride),
                                p[pre + "ds:g"], p[pre + "ds:b"],
                                relu=False)
                h = jnp.maximum(h2 + r, 0.0).astype(cdt)
        h = jnp.mean(h.astype(jnp.float32), axis=(1, 2))
        logits = h @ p["fc:w"] + p["fc:b"]
        logp = jax.nn.log_softmax(logits)
        oh = jax.nn.one_hot(y, 1000)
        return -jnp.mean(jnp.sum(logp * oh, axis=-1))

    def step(p, mom, x, y):
        loss, g = jax.value_and_grad(fwd)(p, x, y)
        mom = {k: 0.9 * mom[k] - 0.05 * g[k] for k in p}
        p = {k: p[k] + mom[k] for k in p}
        return loss, p, mom

    jstep = jax.jit(step, donate_argnums=(0, 1))
    mom = {k: jnp.zeros_like(v) for k, v in params.items()}
    x = jnp.asarray(rng.rand(batch, 224, 224, 3).astype("f")).astype(cdt)
    y = jnp.asarray(rng.randint(0, 1000, batch))
    loss, params, mom = jstep(params, mom, x, y)
    _ = jax.device_get(loss)
    iters = 8
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, params, mom = jstep(params, mom, x, y)
    lv = float(jax.device_get(loss))
    dt = time.perf_counter() - t0
    imgs = batch * iters / dt
    print(json.dumps({
        "metric": "rawjax_resnet50_train_imgs_per_sec_bf16",
        "value": round(imgs, 2), "unit": "img/s",
        "vs_baseline": round(imgs / BASELINE_IMGS_PER_SEC, 3),
        "extra": {"batch": batch, "loss": round(lv, 3),
                  "mfu_pct": mfu_pct(imgs),
                  "note": "no-framework ceiling for the same model"}}))


def io_main():
    """BENCH_MODE=io: input-pipeline throughput — synthetic ImageNet-ish
    .rec -> ImageRecordIter decode + random-crop/mirror + batch, host
    only (no TPU). The number to beat is the chip's consumption rate
    from the training bench (reference: iter_image_recordio_2.cc is
    sized to feed multiple GPUs)."""
    import tempfile

    force_cpu_platform()  # keep jnp math (mean/std normalize) off-tunnel
    import numpy as onp

    from mxnet_tpu import io as mxio, recordio

    n = int(os.environ.get("BENCH_IO_IMAGES", "1024"))
    batch = int(os.environ.get("BENCH_IO_BATCH", "128"))
    threads = int(os.environ.get("BENCH_IO_THREADS",
                                 str(os.cpu_count() or 4)))
    side = 256  # stored size; decode crops to 224
    rec = os.path.join(tempfile.mkdtemp(prefix="bench_io_"), "syn.rec")
    from PIL import Image
    from io import BytesIO

    rng = onp.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(rec + ".idx", rec, "w")
    # a handful of distinct JPEGs cycled n times: realistic decode cost
    # without minutes of synthetic-data generation
    blobs = []
    for i in range(32):
        img = Image.fromarray(
            rng.randint(0, 255, (side, side, 3), "uint8"))
        buf = BytesIO()
        img.save(buf, format="JPEG", quality=90)
        blobs.append(buf.getvalue())
    for i in range(n):
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 1000), i, 0),
            blobs[i % len(blobs)]))
    w.close()

    it = mxio.ImageRecordIter(
        rec, data_shape=(3, 224, 224), batch_size=batch,
        path_imgidx=rec + ".idx", shuffle=True, rand_crop=True,
        rand_mirror=True, mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.1, std_b=57.4,
        preprocess_threads=threads, prefetch_buffer=4)
    seen = 0
    for b in it:  # warmup epoch (JIT of the normalize, page cache)
        seen += b.data[0].shape[0]
    it.reset()
    t0 = time.perf_counter()
    seen = 0
    for b in it:
        b.data[0].wait_to_read()
        seen += b.data[0].shape[0]
    dt = time.perf_counter() - t0
    imgs = seen / dt
    print(json.dumps({
        "metric": "image_record_iter_imgs_per_sec",
        "value": round(imgs, 2), "unit": "img/s", "vs_baseline": 0.0,
        "extra": {"images": seen, "batch": batch,
                  "preprocess_threads": threads,
                  "host_cpus": os.cpu_count(),
                  "imgs_per_sec_per_core": round(imgs / max(
                      1, os.cpu_count() or 1), 2),
                  "decode": "jpeg 256->224 rand-crop+mirror+normalize",
                  "note": "decode scales ~linearly in the native thread "
                          "pool; a real TPU-vM host has ~100+ cores vs "
                          "this box"}}))


# --------------------------------------------------------------- parent ---

def _parse_metric_lines(text):
    """Last valid metric JSON line in `text`, or None."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                if "metric" in json.loads(line):
                    return line
            except ValueError:
                continue
    return None


# per-phase stall budgets (seconds since the child last wrote a phase
# marker). "init" = dialing the tunnel: killing there is safe (no
# compile in flight — the same thing every health probe does). Once a
# compile may be running the child is NEVER killed on a stall shorter
# than the compile budget: a mid-compile SIGKILL wedged the tunnel for
# ~9h in round 4 (BENCH_NOTES_r04.md).
_PHASE_BUDGET = {"init": 240, "devices": 180, "compile": 900,
                 "run": 600, "scoring": 900}
# absolute backstops: killing in init/devices is always safe; once a
# compile may be in flight the backstop is generous (a forced kill
# there risks re-wedging the tunnel — r3/r4 failure mode) and
# overridable via BENCH_ATTEMPT_TIMEOUT
_DIAL_CAP = 1800
_LIVE_CAP = int(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "3600"))


def _read_phase(state):
    try:
        with open(state) as f:
            phase = json.loads(f.read()).get("phase", "init")
        return phase, os.path.getmtime(state)
    except (OSError, ValueError):
        return None, None


def _attempt(platform, timeout):
    """Run the child under phase-aware supervision; return its last
    metric JSON line (possibly from a partially-complete run) or None.
    `timeout` only bounds the CPU-fallback child; the axon child is
    governed by the phase budgets above."""
    env = dict(os.environ, BENCH_CHILD=platform)
    state = env.get("BENCH_STATE", "")
    import tempfile

    with tempfile.TemporaryFile(mode="w+") as outf, \
            tempfile.TemporaryFile(mode="w+") as errf:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=outf, stderr=errf, text=True)
        start = time.monotonic()
        killed_reason = None
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            now = time.monotonic()
            if platform != "axon":
                if now - start > timeout:
                    killed_reason = f"cpu attempt exceeded {timeout}s"
                else:
                    time.sleep(2)
                    continue
            else:
                phase, mtime = _read_phase(state)
                start_wall = time.time() - (now - start)
                if phase is None or (mtime or 0) < start_wall:
                    # no marker from THIS child yet (missing file, or a
                    # stale one from the previous attempt): clock from
                    # this child's spawn, phase init
                    phase, mtime = "init", start_wall
                stall = time.time() - mtime
                budget = _PHASE_BUDGET.get(phase, 600)
                cap = _DIAL_CAP if phase in ("init", "devices") \
                    else _LIVE_CAP
                if now - start > cap:
                    killed_reason = (f"attempt cap {cap}s hit "
                                     f"in phase {phase}")
                elif stall > budget:
                    killed_reason = (f"phase {phase} stalled "
                                     f"{int(stall)}s (> {budget}s)")
                else:
                    time.sleep(5)
                    continue
            # graceful first: SIGTERM lets the child's runtime unwind
            # (finally blocks, PJRT client close) before a hard kill
            print(f"[bench] terminating {platform} child: "
                  f"{killed_reason}", file=sys.stderr)
            proc.terminate()
            try:
                proc.wait(45)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            break
        outf.seek(0)
        stdout = outf.read()
        errf.seek(0)
        stderr = errf.read()
    line = _parse_metric_lines(stdout)
    if line:
        if killed_reason:
            print(f"[bench] salvaged partial result after kill "
                  f"({killed_reason})", file=sys.stderr)
        return line
    tail = (stderr or "")[-2000:]
    print(f"[bench] {platform} attempt rc={proc.returncode} "
          f"{killed_reason or ''}: {tail}", file=sys.stderr)
    return None


def main():
    if CHILD:
        child_main(CHILD)
        return
    if SMOKE:
        smoke_main()
        return
    if os.environ.get("BENCH_MODE") == "io":
        io_main()
        return
    if os.environ.get("BENCH_MODE") == "rawjax":
        rawjax_main()
        return
    if os.environ.get("BENCH_MODE") == "profile":
        profile_main()
        return
    # Budget shape: a WEDGED tunnel dies fast (each attempt ends at the
    # 240s init budget -> ~3 attempts + CPU fallback ≈ 16 min), while a
    # LIVE tunnel gets patience (compile phases are never killed before
    # their 900s budget; partial stdout is salvaged on any kill).
    state = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_state")
    os.environ["BENCH_STATE"] = state
    try:
        os.remove(state)  # stale phases must not skew the kill policy
    except OSError:
        pass
    for i in range(3):
        if i:
            time.sleep(120)  # tunnel recovery window
            # resume the OOM batch-halving descent where the killed
            # attempt left off instead of restarting from scratch
            try:
                with open(state) as f:
                    os.environ["BENCH_RESUME"] = f.read().strip()
            except OSError:
                pass
        line = _attempt("axon", None)
        if line:
            print(line)
            return
    line = _attempt("cpu", 240)
    if line:
        print(line)
        return
    print(json.dumps({"metric": "resnet50_train_imgs_per_sec_fp32",
                      "value": 0.0, "unit": "img/s", "vs_baseline": 0.0,
                      "error": "TPU backend unavailable and CPU fallback "
                               "failed"}))
    raise SystemExit(1)


if __name__ == "__main__":
    main()
