"""Benchmark: ResNet-50 training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: reference MXNet ResNet-50 training, fp32 batch 128 on 1x V100 =
363.69 img/s (BASELINE.md, docs perf.md:243-254). The full training step
(forward, backward, SGD+momentum update, BN stats) is ONE donated XLA
executable built by mxnet_tpu.parallel.SPMDTrainer over a 1-device mesh.

Robustness: the axon TPU tunnel admits one process at a time and its
backend init can hang or fail transiently (round-1 BENCH died at backend
setup). The parent process therefore runs the measurement in a CHILD
subprocess with a per-attempt timeout and retries with backoff; if the TPU
never comes up it falls back to a small CPU measurement so a parsed number
always exists (metric name says which platform produced it).

Env knobs:
  BENCH_BATCH   (default 128; halved on OOM, progress carried across
                retries via BENCH_STATE)
  BENCH_SMOKE=1 tiny-shape CPU smoke for plumbing checks
  BENCH_CHILD   internal: set by the parent to 'axon' or 'cpu'
  BENCH_STATE   internal: file where the child records the last batch
                size it attempted, so a retry resumes the OOM descent
  BENCH_ATTEMPT_TIMEOUT seconds per TPU attempt (default 480)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_IMGS_PER_SEC = 363.69
SMOKE = os.environ.get("BENCH_SMOKE") == "1"
CHILD = os.environ.get("BENCH_CHILD")


from _cpu_platform import force_cpu_platform


# ---------------------------------------------------------------- child ---

def build_trainer(mesh, classes=1000):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu import parallel

    mx.random.seed(0)
    net = vision.resnet50_v1(classes=classes)
    net.initialize(mx.init.Xavier())
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    return parallel.SPMDTrainer(
        net, loss, optimizer="sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
        mesh=mesh)


def run(batch, image_size, classes, warmup=2, iters=8):
    import jax
    import numpy as onp

    from mxnet_tpu import nd, parallel

    mesh = parallel.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = build_trainer(mesh, classes)
    rng = onp.random.RandomState(0)
    x = nd.array(rng.rand(batch, 3, image_size, image_size).astype("f"))
    y = nd.array(rng.randint(0, classes, batch).astype("f"))
    for _ in range(warmup):
        lval = trainer.step(x, y)
    lval.wait_to_read()
    t0 = time.perf_counter()
    for _ in range(iters):
        lval = trainer.step(x, y)
    lval.wait_to_read()
    dt = time.perf_counter() - t0
    return batch * iters / dt, float(lval.asscalar())


def child_main(platform):
    if platform == "cpu":
        force_cpu_platform()
        imgs, _ = run(batch=8, image_size=64, classes=100, warmup=1, iters=4)
        # different workload (64px/100cls) — not comparable to the V100
        # 224px baseline, so vs_baseline stays 0 like the smoke
        print(json.dumps({
            "metric": "resnet50_train_imgs_per_sec_fp32_cpu_fallback",
            "value": round(imgs, 2), "unit": "img/s", "vs_baseline": 0.0}))
        return
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    state = os.environ.get("BENCH_STATE")
    last_err = None
    while batch >= 16:
        if state:
            try:
                with open(state, "w") as f:
                    f.write(str(batch))
            except OSError:
                pass
        try:
            imgs, _ = run(batch=batch, image_size=224, classes=1000)
            print(json.dumps({
                "metric": f"resnet50_train_imgs_per_sec_fp32_b{batch}",
                "value": round(imgs, 2), "unit": "img/s",
                "vs_baseline": round(imgs / BASELINE_IMGS_PER_SEC, 3)}))
            return
        except Exception as e:  # OOM → halve the batch
            last_err = e
            if "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e):
                batch //= 2
                continue
            raise
    raise SystemExit(f"bench failed at batch>=16: {last_err}")


def smoke_main():
    force_cpu_platform()
    imgs, _ = run(batch=4, image_size=32, classes=10, warmup=1, iters=2)
    print(json.dumps({"metric": "resnet50_train_smoke",
                      "value": round(imgs, 2), "unit": "img/s",
                      "vs_baseline": 0.0}))


# --------------------------------------------------------------- parent ---

def _attempt(platform, timeout):
    """Run the child; return its JSON line or None."""
    env = dict(os.environ, BENCH_CHILD=platform)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, timeout=timeout, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        print(f"[bench] {platform} attempt timed out after {timeout}s",
              file=sys.stderr)
        return None
    if proc.returncode == 0:
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    if "metric" in json.loads(line):
                        return line
                except ValueError:
                    continue
    tail = (proc.stderr or "")[-2000:]
    print(f"[bench] {platform} attempt rc={proc.returncode}: {tail}",
          file=sys.stderr)
    return None


def main():
    if CHILD:
        child_main(CHILD)
        return
    if SMOKE:
        smoke_main()
        return
    # total worst-case budget 480+10+480+240 = 1210 s ≈ 20 min if every
    # stage times out — the goal is that a hung tunnel still ends in a
    # printed JSON line, not an rc=124 kill
    t0 = int(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "480"))
    state = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_state")
    os.environ["BENCH_STATE"] = state
    for i in range(2):
        if i:
            time.sleep(10)
            # resume the OOM batch-halving descent where the killed
            # attempt left off instead of restarting at BENCH_BATCH
            try:
                with open(state) as f:
                    os.environ["BENCH_BATCH"] = f.read().strip()
            except (OSError, ValueError):
                pass
        line = _attempt("axon", t0)
        if line:
            print(line)
            return
    line = _attempt("cpu", 240)
    if line:
        print(line)
        return
    print(json.dumps({"metric": "resnet50_train_imgs_per_sec_fp32",
                      "value": 0.0, "unit": "img/s", "vs_baseline": 0.0,
                      "error": "TPU backend unavailable and CPU fallback "
                               "failed"}))
    raise SystemExit(1)


if __name__ == "__main__":
    main()
