"""Benchmark: ResNet-50 training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: reference MXNet ResNet-50 training, fp32 batch 128 on 1x V100 =
363.69 img/s (BASELINE.md, docs perf.md:243-254). The full training step
(forward, backward, SGD+momentum update, BN stats) is ONE donated XLA
executable built by mxnet_tpu.parallel.SPMDTrainer over a 1-device mesh.

Env knobs: BENCH_BATCH (default 128, halved on OOM), BENCH_SMOKE=1 runs a
tiny-shape CPU smoke for plumbing checks.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as onp

SMOKE = os.environ.get("BENCH_SMOKE") == "1"
if SMOKE:
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"

BASELINE_IMGS_PER_SEC = 363.69


def build_trainer(mesh, image_size, classes=1000):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu import parallel

    mx.random.seed(0)
    net = vision.resnet50_v1(classes=classes)
    net.initialize(mx.init.Xavier())
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    return parallel.SPMDTrainer(
        net, loss, optimizer="sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
        mesh=mesh)


def run(batch, image_size, classes, warmup=2, iters=8):
    import jax

    from mxnet_tpu import nd, parallel

    mesh = parallel.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = build_trainer(mesh, image_size, classes)
    rng = onp.random.RandomState(0)
    x = nd.array(rng.rand(batch, 3, image_size, image_size).astype("f"))
    y = nd.array(rng.randint(0, classes, batch).astype("f"))
    for _ in range(warmup):
        lval = trainer.step(x, y)
    lval.wait_to_read()
    t0 = time.perf_counter()
    for _ in range(iters):
        lval = trainer.step(x, y)
    lval.wait_to_read()
    dt = time.perf_counter() - t0
    return batch * iters / dt, float(lval.asscalar())


def main():
    if SMOKE:
        imgs, loss = run(batch=4, image_size=32, classes=10, warmup=1,
                         iters=2)
        print(json.dumps({"metric": "resnet50_train_smoke",
                          "value": round(imgs, 2), "unit": "img/s",
                          "vs_baseline": 0.0}))
        return
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    last_err = None
    while batch >= 16:
        try:
            imgs, loss = run(batch=batch, image_size=224, classes=1000)
            print(json.dumps({
                "metric": f"resnet50_train_imgs_per_sec_fp32_b{batch}",
                "value": round(imgs, 2), "unit": "img/s",
                "vs_baseline": round(imgs / BASELINE_IMGS_PER_SEC, 3)}))
            return
        except Exception as e:  # OOM → halve the batch
            last_err = e
            if "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e):
                batch //= 2
                continue
            raise
    raise SystemExit(f"bench failed at batch>=16: {last_err}")


if __name__ == "__main__":
    main()
