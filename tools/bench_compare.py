#!/usr/bin/env python
"""Diff two BENCH_*.json documents and fail on performance regressions.

Numeric leaves are matched by dotted path; the comparison direction is
inferred from the leaf name:

- lower is better:  ``*_us*``, ``*_ms*``, ``*latency*``, ``*_sec``,
  ``*retrace*`` (compile-count metrics from BENCH_COMPILE_r09.json —
  more retraces in a like-for-like stream is a cache regression),
  ``*p50*``/``*p95*``/``*p99*`` (serving latency quantiles from
  BENCH_SERVE_r10.json — tagged explicitly so a quantile leaf is
  lower-is-better whatever unit suffix it carries), ``*epoch_s*`` /
  ``*idle*`` / ``*stall*`` (epoch-bench wall/idle seconds from
  BENCH_PIPELINE_r11.json — the async pipeline exists to shrink them),
  ``*overhead*`` (checkpoint-overhead metrics from BENCH_RESIL_r12.json
  — async checkpointing is gated at <5% epoch overhead, so growth
  there is a resilience-cost regression — and the tracer-overhead
  gates from BENCH_TELEM_r18.json: ``fused_step_overhead_pct`` /
  ``serving_overhead_pct`` price ``MXNET_TELEMETRY=1`` against ``0``
  on the fused-step loop and serving drain throughput, so growth
  there means instrumentation crept into a hot path; likewise the
  lock-witness gate from BENCH_LOCKCHECK_r22.json:
  ``passthrough_overhead_pct`` prices a level-0 ranked lock against a
  raw ``threading.Lock``, so growth there means the factory stopped
  being a passthrough), ``*nodes*`` /
  ``*trace*``
  (graph-opt metrics from BENCH_GRAPHOPT_r14.json — a like-for-like
  graph lowering to MORE nodes or a longer trace+compile means a
  rewrite pass stopped firing), ``*bytes_moved*`` / ``*accuracy_delta*``
  (int8 serving metrics from BENCH_QUANT_r19.json — the quantized
  path's weight traffic and its deviation from the fp32 answer; growth
  in either means the quantize passes stopped shrinking the model or
  started costing accuracy)
- higher is better: ``*speedup*``, ``*throughput*``, ``*per_sec*``,
  ``*per_s`` (end-anchored: ``steps_per_s`` is throughput but
  ``fused_ms_per_step`` stays latency), ``*items_per*``, ``*_rps*``
  (serving requests/sec), ``*overlap*`` (BENCH_PIPELINE_r11.json
  overlap_ratio
  — the fraction of the feed window not spent stalled; a drop means
  the pipeline stopped hiding the host path), ``*efficiency*``
  (BENCH_SHARD_r15.json scaling-efficiency ratios — the fraction of
  ideal multi-device speedup the sharded fused step actually
  delivers; a drop means the plan-driven partitioning stopped
  scaling), ``*tokens_per*`` (BENCH_DECODE_r16.json decode
  throughput — incremental/continuous-batching tokens per second;
  fewer tokens/s at like-for-like load means the stateful serving
  path re-executed work it should have carried in state slots),
  ``*hit_rate*`` (BENCH_FUSION_r17.json model-zoo cluster hit rate —
  the fraction of fusion-pass decision points that formed a cluster;
  a drop means a matcher or the cost model stopped firing on graphs
  it used to fuse), ``*sessions*`` (BENCH_PAGED_r21.json KV-cache
  capacity — max concurrent sessions resident at a fixed byte budget
  and the paged/row-slot ratios; a drop means paged storage stopped
  packing short prefixes densely), ``*tuned_vs_default*``
  (BENCH_AUTOTUNE_r24.json measured-config over heuristic-default
  cost ratio per decision family — below 1.0 means a persisted
  TuningRecord made a workload SLOWER than the hand-written heuristic
  it replaced). ``*flat_ratio*`` is lower-is-better
  (BENCH_PAGED_r21.json late-prefix over early-prefix step cost —
  growth means decode stopped being O(1) in prefix depth)

Correctness leaves are gated EXACTLY rather than relatively:
``*dropped*`` / ``*corrupted*`` / ``*_must_be_zero`` (fleet
drain/canary gates from BENCH_FLEET_r23.json — a dropped request or a
corrupted migrated session regresses at ANY nonzero value, including
against a zero baseline).

Other numeric leaves (shapes, iteration counts, counters) are ignored.
Exits nonzero when any tracked metric regresses by more than the
threshold (default 20%), so CI can pin benchmark results against a
committed baseline::

    python tools/bench_compare.py BENCH_STEP_r07.json new.json
    python tools/bench_compare.py base.json new.json --threshold 0.1
"""
from __future__ import annotations

import argparse
import json
import sys

LOWER_IS_BETTER = ("_us", "_ms", "latency", "_sec", "retrace",
                   "p50", "p95", "p99", "epoch_s", "idle", "stall",
                   "overhead", "shed", "nodes", "trace",
                   "bytes_moved", "accuracy_delta", "flat_ratio")
HIGHER_IS_BETTER = ("speedup", "throughput", "per_sec",
                    "items_per", "_rps", "overlap", "goodput",
                    "efficiency", "tokens_per", "hit_rate",
                    "sessions", "tuned_vs_default")
# end-anchored: 'steps_per_s' is throughput but 'fused_ms_per_step'
# must stay latency — a bare 'per_s' substring would match both
HIGHER_SUFFIXES = ("per_s",)
# exact-zero correctness gates (BENCH_FLEET_r23.json): a dropped
# request or a corrupted migrated session is a correctness failure,
# not a performance delta — any nonzero candidate value regresses,
# even against a zero baseline the relative rules would skip
EXACT_ZERO = ("dropped", "corrupted")
EXACT_ZERO_SUFFIXES = ("_must_be_zero",)


def _exact_zero(path):
    leaf = path.rsplit(".", 1)[-1].lower()
    return (any(tag in leaf for tag in EXACT_ZERO)
            or leaf.endswith(EXACT_ZERO_SUFFIXES))


def _direction(path):
    leaf = path.rsplit(".", 1)[-1].lower()
    # higher-is-better first: 'items_per_sec' also matches '_sec'
    if any(tag in leaf for tag in HIGHER_IS_BETTER) \
            or leaf.endswith(HIGHER_SUFFIXES):
        return "higher"
    if any(tag in leaf for tag in LOWER_IS_BETTER):
        return "lower"
    return None


def numeric_leaves(doc, prefix=""):
    """{dotted path: value} over all int/float (non-bool) leaves."""
    out = {}
    if isinstance(doc, dict):
        for k, v in doc.items():
            out.update(numeric_leaves(v, f"{prefix}.{k}" if prefix else k))
    elif isinstance(doc, (list, tuple)):
        for i, v in enumerate(doc):
            out.update(numeric_leaves(v, f"{prefix}[{i}]"))
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool):
        out[prefix] = float(doc)
    return out


def compare(base_doc, new_doc, threshold=0.2):
    """Rows of (path, base, new, relative_change, regressed) for every
    tracked metric present in both documents. relative_change > 0 always
    means 'worse' regardless of direction."""
    base = numeric_leaves(base_doc)
    new = numeric_leaves(new_doc)
    rows = []
    for path in sorted(set(base) & set(new)):
        if _exact_zero(path):
            # exact gate: regressed iff the candidate is nonzero; the
            # baseline value is reported but never excuses a failure
            rel = new[path]
            rows.append((path, base[path], new[path], rel,
                         new[path] != 0))
            continue
        direction = _direction(path)
        if direction is None or base[path] == 0:
            continue
        rel = (new[path] - base[path]) / abs(base[path])
        if direction == "higher":
            rel = -rel
        rows.append((path, base[path], new[path], rel, rel > threshold))
    return rows


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("base", help="baseline BENCH_*.json")
    p.add_argument("new", help="candidate BENCH_*.json")
    p.add_argument("--threshold", type=float, default=0.2,
                   help="max tolerated relative regression (default 0.2)")
    a = p.parse_args(argv)
    with open(a.base) as f:
        base_doc = json.load(f)
    with open(a.new) as f:
        new_doc = json.load(f)
    rows = compare(base_doc, new_doc, a.threshold)
    if not rows:
        print("bench_compare: no comparable metrics found")
        return 0
    width = max(len(r[0]) for r in rows)
    regressed = False
    for path, b, n, rel, bad in rows:
        flag = "REGRESSED" if bad else "ok"
        print(f"{path:<{width}}  base={b:<12g} new={n:<12g} "
              f"change={rel * 100:+7.1f}%  {flag}")
        regressed = regressed or bad
    if regressed:
        print(f"bench_compare: regression beyond "
              f"{a.threshold * 100:.0f}% threshold")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
