"""graft_lint — the framework's self-lint: AST-enforced invariants.

The second front end of the static-analysis subsystem (the graph
verifier in ``mxnet_tpu/analysis/`` proves USER graphs safe; this tool
proves the FRAMEWORK itself keeps the invariants those proofs rest on).
Stdlib-only AST checks, plus optional registry checks that import the
package:

``L101 env-read``      a literal ``MXNET_*`` environment variable read
                       outside ``mxnet_tpu/env.py`` — every knob read
                       must go through the env registry helpers
                       (``env.get_int/float/bool/str``) so ``check()``
                       and docs/ENV_VARS.md stay truthful.
``L102 unknown-knob``  a literal ``MXNET_*`` name used anywhere that is
                       not registered in ``env.KNOBS`` — an unregistered
                       knob is invisible to the typo guard and the docs.
``L201 jit-host-sync`` host-side effects inside a jit-compiled body
                       (registered op bodies, ``fused_step`` executable
                       builders, optimizer ``_fused_kernel`` closures):
                       ``time.*``, ``os.environ``, numpy-RNG draws,
                       ``.asnumpy()/.asscalar()/.wait_to_read()``,
                       ``print``. Any of these either breaks tracing or
                       bakes a host value into the executable.
``L202 jit-prng``      ``jax.random.PRNGKey(...)`` inside a jit body —
                       a constant seed baked into the trace replays ONE
                       stream forever; keys must arrive pre-split from
                       the ambient provider (``mxnet_tpu.random``).
``L301 op-docstring``  a ``@register``-decorated op body without a
                       docstring (AST form of the registry R301 check).
``L401 step-sync``     a blocking host sync (``.asnumpy()``,
                       ``.asscalar()``, ``.item()``, ``.wait_to_read()``,
                       ``.block_until_ready()``, ``np.asarray(...)``)
                       inside a step-loop/pipeline module —
                       ``mxnet_tpu/pipeline/``, ``gluon/trainer.py``,
                       or any file carrying the
                       ``# graft-lint: scope(step-loop)`` marker. One
                       stray sync serializes the whole async pipeline
                       (the round-11 overlap win), so the hot path must
                       stay sync-free; deliberate sites (checkpointing,
                       epoch-end metric reads) carry
                       ``# graft-lint: allow(L401)``.
``L601 graph-mutate``  direct mutation of a ``Symbol`` graph-node
                       field (``_op``, ``_inputs``, ``_kwargs``,
                       ``_attrs``, ``_name``, ``_num_outputs``,
                       ``_output_index``, ``_group``) on a non-self
                       receiver outside ``mxnet_tpu/analysis/`` and
                       ``mxnet_tpu/symbol/``. Graph rewrites must go
                       through the pass manager
                       (``analysis/graph_opt.py``), which never
                       mutates shared nodes — an in-place edit
                       corrupts every executor/cache fingerprint that
                       already hashed the graph. Legitimate
                       constructor-adjacent sites (quantization/AMP
                       graph builders, ONNX import) carry
                       ``# graft-lint: allow(L601)``.
``L602 wall-clock``    a ``time.time()`` call inside ``mxnet_tpu/
                       serving/`` or any file carrying the
                       ``# graft-lint: scope(serving-deadline)``
                       marker. Serving deadline/flush math must use
                       the monotonic clock (``time.monotonic()`` for
                       deadlines, ``time.perf_counter()`` for
                       timing): wall clock jumps under NTP steps and
                       DST, and one jump expires every queued request
                       at once (or holds batches forever). A
                       deliberate wall-clock site (log timestamps)
                       carries ``# graft-lint: allow(L602)``.
``L901 raw-counter``   in-place mutation of a module-level counter/
                       stats dict inside ``mxnet_tpu/`` but outside
                       ``mxnet_tpu/telemetry/``. Round 18 moved every
                       counter family into the telemetry
                       MetricsRegistry (``telemetry.metrics.
                       counter_family(...)`` — a one-line binding),
                       so ONE registry feeds the ``/metrics``
                       Prometheus exposition and the Chrome-trace
                       counter samples; a raw ``_COUNTERS[k] += 1``
                       against a module-level dict is invisible to
                       both. Legitimate seed/bootstrap sites carry
                       ``# graft-lint: allow(L901)``.
``L1001 salt-assembly`` ad-hoc cache-salt/fingerprint assembly inside
                       ``mxnet_tpu/`` but outside the artifact layer: a
                       ``fingerprint_salt(...)`` call or a raw
                       ``compile_cache.fingerprint(...)`` composition
                       (alias-aware) anywhere except
                       ``mxnet_tpu/artifact/`` and
                       ``utils/compile_cache.py``. Round 20 moved
                       fingerprint composition behind
                       ``CompiledArtifact``: subsystems contribute salt
                       material by REGISTERING a provider
                       (``artifact.register_salt_provider``) and
                       consumers name it in ``salts=(...)`` — a salt
                       hand-folded into a cache key elsewhere is
                       invisible to that composition and silently
                       diverges from what the disk/remote tiers keyed.
                       Files that DEFINE a provider (``def
                       fingerprint_salt`` / ``register_salt_provider``
                       sites) are the sanctioned sources and are
                       exempt; a deliberate legacy site carries
                       ``# graft-lint: allow(L1001)``.
``L501 bare-except``   a bare ``except:`` clause, or a broad handler
                       (``except Exception``/``BaseException``, alone
                       or in a tuple) whose body is ONLY ``pass``/
                       ``...`` — a silently-swallowed exception. Every
                       fault the resilience layer (round 12) is built
                       to surface can be eaten by one of these; a
                       deliberate best-effort site (``__del__``
                       teardown, optional-dependency probes) carries
                       ``# graft-lint: allow(L501)`` on the except
                       line so the suppression is explicit and
                       reviewable.
``L701 raw-sharding``  a ``NamedSharding(...)`` or ``PartitionSpec``
                       construction inside ``mxnet_tpu/`` but outside
                       ``mxnet_tpu/sharding/`` and
                       ``mxnet_tpu/parallel/`` (alias-aware: the
                       ``from jax.sharding import ... as P`` and
                       ``import jax.sharding as js`` forms are
                       tracked too). Placement decisions must flow
                       from the ShardingPlan rule matcher
                       (``sharding.named_sharding`` / ``replicated`` /
                       ``plan.spec_for``) so ONE declaration drives
                       every consumer; an ad-hoc spec constructed
                       elsewhere silently diverges from the plan. The
                       pre-plan sites that legitimately build their
                       own specs (executor dp-sharding, kvstore
                       key-sharding, MoE expert placement) carry
                       ``# graft-lint: allow(L701)``.
``L801 raw-pallas``    a Pallas import (``import
                       jax.experimental.pallas[.tpu]``, ``from
                       jax.experimental import pallas``, or ``from
                       jax.experimental.pallas[...] import ...``)
                       inside ``mxnet_tpu/`` but outside
                       ``mxnet_tpu/kernels/``. Hand-scheduled kernels
                       live in ONE package behind registered fused ops
                       with lax fallbacks, so every Pallas call site
                       sits behind the fusion cost model, the
                       ``MXNET_FUSION`` kill switch and the
                       interpret-mode parity tests; an import
                       elsewhere bypasses all three. A deliberate
                       site carries ``# graft-lint: allow(L801)``.
``jit-nocache``        a raw ``jax.jit`` call site inside ``mxnet_tpu/``
                       that bypasses the compile-cache helpers
                       (``utils.compile_cache.counting_jit`` or the AOT
                       serialize path): raw sites are invisible to the
                       retrace counter and the persistent compile
                       cache. Deliberate bypasses (one-shot equivalence
                       checks, raw-jit benchmarks) carry
                       ``# graft-lint: allow(jit-nocache)``.
``L1101 raw-lock``     a ``threading.Lock/RLock/Condition(...)``
                       construction inside ``mxnet_tpu/`` but outside
                       ``utils/locks.py`` (alias-aware: ``import
                       threading as _t`` and ``from threading import
                       Lock as L`` are tracked). Round 22 moved every
                       lock onto the ranked-lock registry
                       (``utils.locks.RankedLock/RankedRLock/
                       RankedCondition``) so the lock-order witness
                       sees it; a raw lock is invisible to the
                       deadlock witness and has no declared rank. The
                       handful of deliberately unranked sites
                       (benchmark harnesses, the witness's own
                       internals) carry ``# graft-lint: allow(L1101)``.
``L1102 guarded-by``   an attribute declared in a ``# guards: _a, _b``
                       comment on a ranked-lock assignment, accessed
                       in a method/function of the same scope that
                       does not hold that lock (``with self._lock:``
                       blocks, ``lock = self._lock`` /
                       ``getattr(self, "_lock", ...)`` aliases and
                       ``.acquire()``-style methods are recognized;
                       ``__init__`` and ``*_locked``-suffix
                       methods — the store's caller-holds-the-lock
                       convention — are exempt). A deliberate
                       unlocked fast path (documented racy read,
                       atomic-len probe) carries
                       ``# graft-lint: allow(L1102)`` with a reason.
``L1103 block-under-lock`` a blocking call lexically inside a ``with
                       <ranked-lock>:`` body: host syncs
                       (``.asnumpy()/.asscalar()/.wait_to_read()/
                       .block_until_ready()``), ``time.sleep``,
                       ``open(...)``/``urlopen(...)`` file/HTTP IO, or
                       a ``RetryPolicy`` construction/run. One sleep
                       or device sync under a hot-path lock convoys
                       every thread behind it (the r21 paged-store
                       rule "pool operands are indexed OUTSIDE the
                       store lock", now machine-checked). A site
                       where the block is the point (a condition
                       wait's timeout loop) carries
                       ``# graft-lint: allow(L1103)``.
``L1201 policy-literal`` a numeric performance-policy threshold in the
                       fusion cost-model files (``kernels/
                       cost_model.py``, ``analysis/fusion.py``) that
                       did not go through the autotune DecisionPoint
                       registry: a module-level ALL-CAPS constant
                       assigned a bare numeric-literal expression
                       (``1 << 22`` counts) instead of a
                       ``declare_decision(...)`` result, or an inline
                       comparison against a numeric literal above the
                       structural range (|n| > 8 — ``len(x) >= 2`` and
                       ``== 0`` stay exempt). Round 24 made measured
                       records beat hand-written thresholds; a bare
                       literal is invisible to the tuner and to
                       ``docs/AUTOTUNE.md``'s decision-point table.
                       Hardware geometry (tile floors) carries
                       ``# graft-lint: allow(L1201)``.
``R301/R302/R303``     registry checks (``--registry``): every
                       registered op carries a docstring; every op named
                       in the dtype-rule tables of ``symbol/infer.py``
                       and the structural tables of ``symbol/__init__``
                       is actually registered; every registered op's
                       output dtype is resolvable by ``_node_out_dtype``.

Suppress a finding with a same-line pragma: ``# graft-lint: allow(L101)``.

Usage::

    python -m tools.graft_lint [paths...]     # default: mxnet_tpu
    python -m tools.graft_lint --no-registry mxnet_tpu

Exit status 0 iff no findings. Runs inside tier-1 via
tests/test_graft_lint.py.
"""
from __future__ import annotations

import argparse
import ast
import os
import sys

ENV_MODULE = os.path.join("mxnet_tpu", "env.py")
ENV_HELPERS = {"get_int", "get_float", "get_bool", "get_str"}


class Finding:
    def __init__(self, code, path, line, message):
        self.code = code
        self.path = path
        self.line = line
        self.message = message

    def __str__(self):
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _literal_env_name(node):
    """The literal MXNET_* string of an env access, if any."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("MXNET_"):
        return node.value
    return None


def _is_os_environ(node):
    """node is an ``environ`` expression — ``os.environ``, an aliased
    ``_os.environ``, or a bare imported ``environ``."""
    if isinstance(node, ast.Attribute) and node.attr == "environ" \
            and isinstance(node.value, ast.Name):
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def _dotted(node):
    """'a.b.c' for an attribute chain over Names, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def load_registered_knobs(repo_root):
    """KNOBS keys parsed out of mxnet_tpu/env.py without importing it."""
    path = os.path.join(repo_root, ENV_MODULE)
    try:
        tree = ast.parse(open(path).read(), path)
    except OSError:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "KNOBS" \
                        and isinstance(node.value, ast.Dict):
                    return {k.value for k in node.value.keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str)}
    return None


# ---------------------------------------------------------------------------
# per-file checks

class _Pragmas:
    def __init__(self, source):
        self._allow = {}
        for i, line in enumerate(source.splitlines(), 1):
            if "graft-lint:" in line:
                frag = line.split("graft-lint:", 1)[1]
                if "allow(" in frag:
                    codes = frag.split("allow(", 1)[1].split(")")[0]
                    self._allow[i] = {c.strip() for c in codes.split(",")}

    def allows(self, line, code):
        return code in self._allow.get(line, ())


def check_env_discipline(path, tree, source, knobs, findings):
    """L101 + L102 over one parsed file."""
    is_env_module = path.replace(os.sep, "/").endswith("mxnet_tpu/env.py")
    pragmas = _Pragmas(source)

    def emit(code, node, msg):
        if not pragmas.allows(node.lineno, code):
            findings.append(Finding(code, path, node.lineno, msg))

    for node in ast.walk(tree):
        name = None
        is_read = False
        if isinstance(node, ast.Subscript) and _is_os_environ(node.value):
            name = _literal_env_name(node.slice)
            is_read = not isinstance(getattr(node, "ctx", None),
                                     (ast.Store, ast.Del))
        elif isinstance(node, ast.Call):
            fn = node.func
            dn = _dotted(fn)
            if dn and (dn.endswith(".environ.get") or dn in
                       ("environ.get", "os.getenv", "getenv")):
                name = _literal_env_name(node.args[0]) if node.args \
                    else None
                is_read = True
            elif isinstance(fn, ast.Attribute) and \
                    fn.attr in ENV_HELPERS and node.args:
                # env.get_int("MXNET_X", ...) — blessed read; still
                # requires the knob to be registered (L102)
                name = _literal_env_name(node.args[0])
                if name and knobs is not None and name not in knobs:
                    emit("L102", node,
                         f"env knob {name!r} is not registered in "
                         "mxnet_tpu/env.py KNOBS")
                continue
            elif dn and (dn.endswith(".environ.pop") or
                         dn.endswith(".environ.setdefault") or
                         dn in ("environ.pop", "environ.setdefault")):
                continue  # writes/clears are not knob reads
        if name and is_read and not is_env_module:
            emit("L101", node,
                 f"direct environment read of {name!r}; use "
                 "mxnet_tpu.env.get_int/get_float/get_bool/get_str")
        if name and knobs is not None and name not in knobs:
            emit("L102", node,
                 f"env knob {name!r} is not registered in "
                 "mxnet_tpu/env.py KNOBS")


# -- jit-body scopes --------------------------------------------------------

def _op_registry_names(tree):
    """Local names that ``register`` from an op-registry module is bound
    to in this file (``from .registry import register`` / ``from
    mxnet_tpu.ndarray.registry import register``). Keeps the jit-scope
    detection semantic — other ``register`` decorators (optimizer
    classes, metric classes, embeddings) are not op bodies."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[-1] == "registry":
            for a in node.names:
                if a.name == "register":
                    names.add(a.asname or a.name)
    return names


def _has_register_decorator(fn, reg_names=("register",)):
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        dn = _dotted(target)
        if dn and dn.split(".")[-1] in reg_names:
            return True
    return False


def collect_jit_scopes(path, tree):
    """[(FunctionDef, label)] whose bodies execute under jax.jit."""
    norm = path.replace(os.sep, "/")
    scopes = []
    base = os.path.basename(norm)
    in_ops_file = "/ndarray/" in norm and base.startswith("ops_")
    reg_names = _op_registry_names(tree)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if reg_names and _has_register_decorator(node, reg_names):
            scopes.append((node, f"op '{node.name}'"))
        elif in_ops_file and node.name == "op":
            # factory-produced op bodies (_make_unary/_scalar_pair/...)
            scopes.append((node, "factory op body"))
        elif norm.endswith("gluon/fused_step.py"):
            if node.name == "build_executable":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.FunctionDef) \
                            and sub is not node:
                        scopes.append(
                            (sub, f"fused-step body '{sub.name}'"))
        elif norm.endswith("optimizer/optimizer.py") \
                and node.name == "_fused_kernel":
            for sub in ast.walk(node):
                if isinstance(sub, ast.FunctionDef) and sub is not node:
                    scopes.append(
                        (sub, f"fused kernel '{sub.name}'"))
    # de-dup (nested walk may visit twice)
    seen, out = set(), []
    for fn, label in scopes:
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append((fn, label))
    return out


_HOST_SYNC_CALLS = {"asnumpy", "asscalar", "wait_to_read",
                    "block_until_ready", "item"}
_TIME_MODULES = {"time", "_time"}
_NP_MODULES = {"np", "onp", "numpy"}


def check_jit_safety(path, tree, source, findings):
    pragmas = _Pragmas(source)
    seen = set()  # (code, line): nested Attribute walks hit chains twice

    def emit(code, node, label, msg):
        if pragmas.allows(node.lineno, code) or \
            (code, node.lineno) in seen:
            return
        seen.add((code, node.lineno))
        findings.append(
            Finding(code, path, node.lineno, f"{msg} inside "
                    f"jit-compiled {label}"))

    for fn, label in collect_jit_scopes(path, tree):
        for node in ast.walk(fn):
            dn = _dotted(node) if isinstance(node, ast.Attribute) else None
            if dn:
                root, *rest = dn.split(".")
                if root in _TIME_MODULES and rest:
                    emit("L201", node, label,
                         f"host clock access '{dn}'")
                elif root in _NP_MODULES and rest \
                        and rest[0] == "random":
                    emit("L201", node, label,
                         f"host numpy RNG '{dn}' (draws once at trace "
                         "time)")
                elif dn.startswith("os.environ"):
                    emit("L201", node, label, "os.environ read")
                elif dn == "jax.random.PRNGKey":
                    emit("L202", node, label,
                         "constant PRNGKey (un-split key baked into "
                         "the trace); draw from the ambient provider")
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in _HOST_SYNC_CALLS:
                    emit("L201", node, label,
                         f"host sync '.{f.attr}()'")
                elif isinstance(f, ast.Name) and f.id == "print":
                    emit("L201", node, label, "print()")


_STEP_SYNC_ATTRS = {"asnumpy", "asscalar", "item", "wait_to_read",
                    "block_until_ready"}


def _step_loop_scoped(path, source):
    """Files the L401 step-sync discipline applies to: the pipeline
    package and the Trainer step loop are scoped automatically (a new
    pipeline module can't silently opt out); other step-loop code opts
    in with a ``# graft-lint: scope(step-loop)`` marker."""
    norm = path.replace(os.sep, "/")
    if "mxnet_tpu/pipeline/" in norm or norm.endswith("gluon/trainer.py"):
        return True
    return "graft-lint: scope(step-loop)" in source


def check_step_host_sync(path, tree, source, findings):
    """L401: blocking host syncs inside step-loop/pipeline modules.
    Each one stalls the consuming thread until the device (or a worker)
    catches up — exactly the serialization the async pipeline exists to
    remove — so the hot path must route them off-path (device-resident
    metrics, epoch-end reads) or whitelist them explicitly."""
    if not _step_loop_scoped(path, source):
        return
    pragmas = _Pragmas(source)
    seen = set()

    def emit(node, msg):
        if pragmas.allows(node.lineno, "L401") or node.lineno in seen:
            return
        seen.add(node.lineno)
        findings.append(Finding(
            "L401", path, node.lineno,
            f"{msg} in a step-loop/pipeline module serializes the "
            "async pipeline; defer it off the hot path or annotate a "
            "deliberate site with allow(L401)"))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _STEP_SYNC_ATTRS:
            emit(node, f"blocking host sync '.{f.attr}()'")
            continue
        dn = _dotted(f)
        if dn:
            root, *rest = dn.split(".")
            if root in _NP_MODULES and rest in (["asarray"], ["array"]):
                emit(node, f"blocking device→host transfer '{dn}(...)'")


def _serving_deadline_scoped(path, source):
    """Files the L602 monotonic-clock discipline applies to: the
    serving package is scoped automatically (every queue exit there
    does deadline math; a new serving module can't silently opt out);
    other deadline code opts in with a
    ``# graft-lint: scope(serving-deadline)`` marker."""
    norm = path.replace(os.sep, "/")
    if "mxnet_tpu/serving/" in norm:
        return True
    return "graft-lint: scope(serving-deadline)" in source


def check_wallclock_deadlines(path, tree, source, findings):
    """L602: ``time.time()`` in deadline-scoped modules. Deadlines and
    flush timers compare against ``time.monotonic()`` everywhere else
    in serving/; one wall-clock read mixed in breaks the comparison
    the moment NTP steps the clock."""
    if not _serving_deadline_scoped(path, source):
        return
    pragmas = _Pragmas(source)
    # `from time import time` makes the call site a bare Name — track
    # the aliases that import form introduces so it can't hide
    bare_aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    bare_aliases.add(a.asname or a.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        dn = _dotted(f)
        hit = (dn is not None and dn.split(".")[-1] == "time" and
               dn.split(".")[0].lstrip("_") == "time") or \
              (isinstance(f, ast.Name) and f.id in bare_aliases)
        if hit and not pragmas.allows(node.lineno, "L602"):
            findings.append(Finding(
                "L602", path, node.lineno,
                "wall-clock time.time() in a serving/deadline module; "
                "deadline math must use time.monotonic() (and timing "
                "time.perf_counter()) — annotate a deliberate "
                "wall-clock site (log timestamps) with allow(L602)"))


#: Symbol graph-node fields whose in-place mutation rewires a graph
#: other code may already hold / have fingerprinted
_SYMBOL_NODE_ATTRS = {"_op", "_inputs", "_kwargs", "_attrs", "_name",
                      "_num_outputs", "_output_index", "_group"}

#: container methods that mutate their receiver
_MUTATOR_METHODS = {"update", "append", "extend", "insert", "pop",
                    "clear", "setdefault", "remove", "popitem"}


def _graph_rewrite_scoped(path, source):
    """Files the L601 no-graph-mutation discipline applies to: all of
    ``mxnet_tpu/`` EXCEPT the pass manager itself (``analysis/``) and
    the Symbol constructors (``symbol/``), which own those fields.
    Code outside the package opts in with a
    ``# graft-lint: scope(symbol-graph)`` marker."""
    norm = path.replace(os.sep, "/")
    if "mxnet_tpu/analysis/" in norm or "mxnet_tpu/symbol/" in norm:
        return False
    if "mxnet_tpu/" in norm:
        return True
    return "graft-lint: scope(symbol-graph)" in source


def _node_attr_target(expr):
    """The ``x._inputs``-shaped Attribute under ``expr`` (direct, or
    through a subscript like ``x._kwargs["shape"]``), or None."""
    if isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Attribute) and \
            expr.attr in _SYMBOL_NODE_ATTRS:
        return expr
    return None


def check_graph_mutation(path, tree, source, findings):
    """L601: in-place mutation of Symbol graph-node fields outside the
    pass manager. Symbols are shared DAG nodes: executors, the compile
    caches and the serving fingerprints all key off a graph's identity
    and serialized form, so an in-place ``node._inputs.append(...)`` or
    ``node._op = ...`` silently invalidates every one of them. Rewrites
    construct fresh nodes via ``analysis/graph_opt.py``; ``self``/
    ``cls`` receivers (a class managing its own fields) are exempt."""
    if not _graph_rewrite_scoped(path, source):
        return
    pragmas = _Pragmas(source)

    def self_receiver(attr_node):
        return isinstance(attr_node.value, ast.Name) and \
            attr_node.value.id in ("self", "cls")

    def emit(node, attr_node, what):
        if pragmas.allows(node.lineno, "L601"):
            return
        findings.append(Finding(
            "L601", path, node.lineno,
            f"direct graph-node mutation: {what} "
            f"'{attr_node.attr}' outside mxnet_tpu/analysis/ — rewires "
            "a possibly-shared Symbol DAG under executors and cache "
            "fingerprints; build fresh nodes through the pass manager "
            "(analysis/graph_opt.py) or annotate a constructor-"
            "adjacent site with allow(L601)"))

    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = node.targets if isinstance(node, ast.Assign) else \
                (node.targets if isinstance(node, ast.Delete)
                 else [node.target])
            for t in targets:
                attr = _node_attr_target(t)
                if attr is not None and not self_receiver(attr):
                    emit(node, attr, "deletion of"
                         if isinstance(node, ast.Delete)
                         else "assignment to")
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATOR_METHODS:
            attr = _node_attr_target(node.func.value)
            if attr is not None and not self_receiver(attr):
                emit(node, attr,
                     f"mutating call '.{node.func.attr}()' on")


#: jax.sharding classes whose raw construction outside the sharding
#: subsystem bypasses the plan rule matcher
_SHARDING_CLASSES = {"NamedSharding", "PartitionSpec"}


def _sharding_construction_scoped(path, source):
    """Files the L701 plan-discipline applies to: all of ``mxnet_tpu/``
    EXCEPT the sharding subsystem itself and ``parallel/`` (the mesh/
    spec primitives those two own). Code outside the package opts in
    with a ``# graft-lint: scope(sharding-plan)`` marker."""
    norm = path.replace(os.sep, "/")
    if "mxnet_tpu/sharding/" in norm or "mxnet_tpu/parallel/" in norm:
        return False
    if "mxnet_tpu/" in norm:
        return True
    return "graft-lint: scope(sharding-plan)" in source


def check_raw_sharding_construction(path, tree, source, findings):
    """L701: raw ``NamedSharding``/``PartitionSpec`` construction
    outside the sharding subsystem. The round-15 contract is ONE
    declaration (the ShardingPlan) driving every consumer; a spec
    hand-built elsewhere is invisible to the plan (and to its
    fingerprint salt), so the fused step, serving and checkpoints
    would disagree about a buffer's layout. Alias-tracked like L602:
    ``from jax.sharding import PartitionSpec as P`` and
    ``import jax.sharding as js`` can't hide the call site."""
    if not _sharding_construction_scoped(path, source):
        return
    pragmas = _Pragmas(source)
    aliases = {}      # local callable name -> jax.sharding class
    mod_aliases = set()  # names bound to the jax.sharding module
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and \
                node.module == "jax.sharding":
            for a in node.names:
                if a.name in _SHARDING_CLASSES:
                    aliases[a.asname or a.name] = a.name
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.sharding":
                    mod_aliases.add(a.asname or "jax.sharding")
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        cls = None
        if isinstance(f, ast.Name) and f.id in aliases:
            cls = aliases[f.id]
        else:
            dn = _dotted(f)
            if dn is not None:
                head, _, last = dn.rpartition(".")
                if last in _SHARDING_CLASSES and (
                        head == "jax.sharding" or head in mod_aliases):
                    cls = last
        if cls is not None and not pragmas.allows(node.lineno, "L701"):
            findings.append(Finding(
                "L701", path, node.lineno,
                f"raw {cls} construction outside mxnet_tpu/sharding/ "
                "+ parallel/ — placement must flow from the "
                "ShardingPlan (sharding.named_sharding/replicated or "
                "plan.spec_for), so one declaration drives every "
                "consumer; annotate a deliberate pre-plan site with "
                "allow(L701)"))


_PALLAS_MODULE = "jax.experimental.pallas"


def _pallas_import_scoped(path, source):
    """Files the L801 kernel-discipline applies to: all of
    ``mxnet_tpu/`` EXCEPT ``mxnet_tpu/kernels/`` (the one package that
    owns Pallas code). Code outside the package opts in with a
    ``# graft-lint: scope(pallas-kernels)`` marker."""
    norm = path.replace(os.sep, "/")
    if "mxnet_tpu/kernels/" in norm:
        return False
    if "mxnet_tpu/" in norm:
        return True
    return "graft-lint: scope(pallas-kernels)" in source


def check_raw_pallas_import(path, tree, source, findings):
    """L801: a Pallas import outside ``mxnet_tpu/kernels/``. The
    round-17 contract mirrors L701's: hand-scheduled kernels live in
    ONE package, behind registered fused ops with lax fallbacks, so
    every Pallas call site is reachable by the cost model, the
    ``MXNET_FUSION`` kill switch, and the interpret-mode parity tests.
    A Pallas import elsewhere bypasses all three. Catches ``import
    jax.experimental.pallas[.tpu]``, ``from jax.experimental import
    pallas``, and ``from jax.experimental.pallas[.x] import ...``."""
    if not _pallas_import_scoped(path, source):
        return
    pragmas = _Pragmas(source)
    for node in ast.walk(tree):
        hit = False
        if isinstance(node, ast.Import):
            hit = any(a.name == _PALLAS_MODULE or
                      a.name.startswith(_PALLAS_MODULE + ".")
                      for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            hit = (mod == _PALLAS_MODULE or
                   mod.startswith(_PALLAS_MODULE + ".") or
                   (mod == "jax.experimental" and
                    any(a.name == "pallas" for a in node.names)))
        if hit and not pragmas.allows(node.lineno, "L801"):
            findings.append(Finding(
                "L801", path, node.lineno,
                "Pallas import outside mxnet_tpu/kernels/ — "
                "hand-scheduled kernels live in the kernels package "
                "behind registered fused ops (cost model + "
                "MXNET_FUSION gate + interpret parity tests); "
                "annotate a deliberate site with allow(L801)"))


def _counter_registry_scoped(path, source):
    """Files the L901 counter-registry discipline applies to: all of
    ``mxnet_tpu/`` EXCEPT the telemetry package itself (which owns the
    CounterFamily primitive). Code outside the package opts in with a
    ``# graft-lint: scope(counter-registry)`` marker."""
    norm = path.replace(os.sep, "/")
    if "mxnet_tpu/telemetry/" in norm:
        return False
    if "mxnet_tpu/" in norm:
        return True
    return "graft-lint: scope(counter-registry)" in source


def _counterish_name(name):
    """Module-level names that read as counter/stat stores."""
    return name == name.upper() and (
        "COUNTER" in name or "STATS" in name or
        name.endswith("_COUNTS"))


def _raw_counter_value(value):
    """True when the bound value is a raw mutable mapping — a dict
    literal/comprehension, ``dict(...)``, ``dict.fromkeys(...)`` or a
    ``_zero*()`` template builder — rather than a registry-owned
    ``counter_family(...)`` binding."""
    if isinstance(value, (ast.Dict, ast.DictComp)):
        return True
    if isinstance(value, ast.Call):
        dn = _dotted(value.func) or ""
        last = dn.split(".")[-1]
        return dn == "dict" or last == "fromkeys" or \
            last.startswith("_zero") or last.startswith("zero_")
    return False


def check_raw_counter_mutation(path, tree, source, findings):
    """L901: in-place mutation of a module-level raw counter dict.
    Since round 18 every counter family lives in the telemetry
    MetricsRegistry (``telemetry.metrics.counter_family``) so the
    unified ``/metrics`` exposition and the Chrome-trace counter
    samples see one source of truth; a module-level ``{...}`` bumped
    in place is invisible to both surfaces and races without the
    family's lock."""
    if not _counter_registry_scoped(path, source):
        return
    raw = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and \
                _raw_counter_value(node.value):
            raw.update(t.id for t in node.targets
                       if isinstance(t, ast.Name)
                       and _counterish_name(t.id))
    if not raw:
        return
    pragmas = _Pragmas(source)
    seen = set()

    def emit(node, what, name):
        if pragmas.allows(node.lineno, "L901") or node.lineno in seen:
            return
        seen.add(node.lineno)
        findings.append(Finding(
            "L901", path, node.lineno,
            f"{what} module-level raw counter dict '{name}' — bind it "
            "through telemetry.metrics.counter_family(...) so the "
            "unified /metrics exposition and trace counter samples "
            "see it (one-line change), or annotate a deliberate "
            "bootstrap site with allow(L901)"))

    def raw_subscript(t):
        return isinstance(t, ast.Subscript) and \
            isinstance(t.value, ast.Name) and t.value.id in raw

    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if raw_subscript(t):
                    emit(node, "in-place write to", t.value.id)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if raw_subscript(t):
                    emit(node, "deletion from", t.value.id)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATOR_METHODS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in raw:
            emit(node, f"mutating call '.{node.func.attr}()' on",
                 node.func.value.id)


def _salt_discipline_scoped(path, source):
    """Files the L1001 salt discipline applies to: all of
    ``mxnet_tpu/`` EXCEPT the artifact package (which owns fingerprint
    composition), ``utils/compile_cache.py`` (the digest primitive
    itself), and any file that DEFINES a salt provider — providers are
    the sanctioned way for a subsystem to contribute salt material.
    Code outside the package opts in with a
    ``# graft-lint: scope(salt-providers)`` marker."""
    norm = path.replace(os.sep, "/")
    if "mxnet_tpu/artifact/" in norm or \
            norm.endswith("mxnet_tpu/utils/compile_cache.py"):
        return False
    if "def fingerprint_salt" in source or \
            "register_salt_provider" in source:
        return False
    if "mxnet_tpu/" in norm:
        return True
    return "graft-lint: scope(salt-providers)" in source


def check_salt_assembly(path, tree, source, findings):
    """L1001: ad-hoc salt/fingerprint assembly outside the artifact
    layer. Round 20's contract is ONE fingerprint composition path
    (``CompiledArtifact`` resolving declared salt providers): a
    ``fingerprint_salt(...)`` call or raw ``compile_cache.
    fingerprint(...)`` elsewhere folds key material the artifact layer
    never sees, so the same executable fingerprints differently across
    call sites and the disk/remote tiers silently miss."""
    if not _salt_discipline_scoped(path, source):
        return
    fp_aliases = set()  # local names bound to compile_cache.fingerprint
    cc_aliases = set()  # local names bound to the compile_cache module
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.endswith("compile_cache"):
                for a in node.names:
                    if a.name == "fingerprint":
                        fp_aliases.add(a.asname or a.name)
            elif mod.endswith("utils"):
                for a in node.names:
                    if a.name == "compile_cache":
                        cc_aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("compile_cache"):
                    cc_aliases.add(a.asname or a.name)
    pragmas = _Pragmas(source)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        what = None
        if (isinstance(f, ast.Name) and f.id == "fingerprint_salt") or \
                (isinstance(f, ast.Attribute)
                 and f.attr == "fingerprint_salt"):
            what = "fingerprint_salt(...) salt assembly"
        elif isinstance(f, ast.Name) and f.id in fp_aliases:
            what = "raw compile_cache.fingerprint(...) composition"
        elif isinstance(f, ast.Attribute) and f.attr == "fingerprint" \
                and isinstance(f.value, ast.Name) \
                and f.value.id in cc_aliases:
            what = "raw compile_cache.fingerprint(...) composition"
        if what is not None and not pragmas.allows(node.lineno, "L1001"):
            findings.append(Finding(
                "L1001", path, node.lineno,
                f"{what} outside mxnet_tpu/artifact/ — register a salt "
                "provider (artifact.register_salt_provider) and name it "
                "in CompiledArtifact(salts=...) so fingerprint "
                "composition stays in one layer; annotate a deliberate "
                "legacy site with allow(L1001)"))


_BROAD_EXC = {"Exception", "BaseException"}


def check_swallowed_exceptions(path, tree, source, findings):
    """L501: bare ``except:`` and silently-swallowed broad handlers.
    A bare clause is flagged regardless of body (it also eats
    SystemExit/KeyboardInterrupt); a typed Exception/BaseException
    handler is flagged only when its body is nothing but ``pass``/
    ``...`` — no log line, no counter, no re-raise, no fallback value
    — because that is the shape that turns a real fault into silence."""
    pragmas = _Pragmas(source)

    def exc_names(t):
        if t is None:
            return [None]
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        out = []
        for e in elts:
            if isinstance(e, ast.Name):
                out.append(e.id)
            elif isinstance(e, ast.Attribute):
                out.append(e.attr)
            else:
                out.append(None)
        return out

    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if pragmas.allows(node.lineno, "L501"):
            continue
        bare = node.type is None
        broad = bare or any(n in _BROAD_EXC
                            for n in exc_names(node.type))
        swallowed = all(
            isinstance(s, ast.Pass) or
            (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant)
             and s.value.value is Ellipsis)
            for s in node.body)
        if bare:
            findings.append(Finding(
                "L501", path, node.lineno,
                "bare 'except:' swallows SystemExit/KeyboardInterrupt "
                "too; catch a concrete type (or annotate a deliberate "
                "site with allow(L501))"))
        elif broad and swallowed:
            findings.append(Finding(
                "L501", path, node.lineno,
                "broad exception handler silently swallows the error "
                "(body is only pass); log/count/re-raise it, or "
                "annotate a deliberate best-effort site with "
                "allow(L501)"))


def check_jit_nocache(path, tree, source, findings):
    """jit-nocache: raw ``jax.jit(...)`` call sites must route through
    the compile-cache helpers or carry an allow pragma."""
    norm = path.replace(os.sep, "/")
    if norm.endswith("mxnet_tpu/utils/compile_cache.py"):
        return  # the helpers themselves own the one legitimate raw site
    pragmas = _Pragmas(source)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _dotted(node.func) == "jax.jit" \
                and not pragmas.allows(node.lineno, "jit-nocache"):
            findings.append(Finding(
                "jit-nocache", path, node.lineno,
                "raw jax.jit call site bypasses the compile-cache "
                "helpers (use utils.compile_cache.counting_jit, or "
                "annotate a deliberate bypass)"))


def check_op_docstrings(path, tree, source, findings):
    reg_names = _op_registry_names(tree)
    if not reg_names:
        return
    pragmas = _Pragmas(source)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) \
                and _has_register_decorator(node, reg_names) \
                and ast.get_docstring(node) is None \
                and not pragmas.allows(node.lineno, "L301"):
            findings.append(Finding(
                "L301", path, node.lineno,
                f"registered op '{node.name}' has no docstring"))


# ---------------------------------------------------------------------------
# registry checks (import the package)

def registry_checks(findings):
    """R301 doc coverage, R302 table consistency, R303 dtype-rule
    resolvability — over the LIVE registry, so factory-generated ops
    (whose docstrings the AST cannot see) are covered too."""
    from mxnet_tpu.ndarray import registry as _registry
    from mxnet_tpu.symbol import _AUTO_PARAMS, _AUX_INPUT_SLOTS
    from mxnet_tpu.symbol.infer import (_FIXED_OUT_DTYPE,
                                        _PARAM_DTYPE_DEFAULTS,
                                        _node_out_dtype)

    loc = "mxnet_tpu/ndarray/registry.py"
    for name in _registry.list_ops():
        opdef = _registry.get_op(name)
        if not (opdef.doc or "").strip():
            findings.append(Finding(
                "R301", loc, 0,
                f"registered op '{name}' has no docstring"))
        try:
            _node_out_dtype(name, {}, {})
        except Exception as e:
            findings.append(Finding(
                "R303", "mxnet_tpu/symbol/infer.py", 0,
                f"output dtype of op '{name}' is not resolvable: {e}"))
    for table, where in ((_FIXED_OUT_DTYPE, "symbol/infer.py "
                          "_FIXED_OUT_DTYPE"),
                         (_PARAM_DTYPE_DEFAULTS, "symbol/infer.py "
                          "_PARAM_DTYPE_DEFAULTS"),
                         (_AUTO_PARAMS, "symbol/__init__ _AUTO_PARAMS"),
                         (_AUX_INPUT_SLOTS, "symbol/__init__ "
                          "_AUX_INPUT_SLOTS")):
        for opname in table:
            if _registry.get_op(opname) is None:
                findings.append(Finding(
                    "R302", "mxnet_tpu/symbol/infer.py", 0,
                    f"{where} names unregistered op '{opname}'"))


# ---------------------------------------------------------------------------
# L1101/L1102/L1103 — lock discipline (round 22)

_RANKED_CTORS = {"RankedLock", "RankedRLock", "RankedCondition"}

_BLOCKING_ATTRS = {"asnumpy", "asscalar", "wait_to_read",
                   "block_until_ready"}


def _ranked_lock_scoped(path, source):
    """Files the lock discipline applies to: all of ``mxnet_tpu/``
    except ``utils/locks.py`` (which owns the primitive and the
    witness's own raw internals). Code outside the package opts in
    with a ``# graft-lint: scope(ranked-locks)`` marker (fixtures)."""
    norm = path.replace(os.sep, "/")
    if norm.endswith("mxnet_tpu/utils/locks.py"):
        return False
    if "mxnet_tpu/" in norm:
        return True
    return "graft-lint: scope(ranked-locks)" in source


def check_raw_lock_construction(path, tree, source, findings):
    """L1101: a raw ``threading.Lock/RLock/Condition(...)`` call.
    Every lock must come from the ranked-lock factories in
    ``utils/locks.py`` so it carries a name and a place in the single
    declared lock order — a raw lock is invisible to the runtime
    deadlock witness."""
    if not _ranked_lock_scoped(path, source):
        return
    mod_aliases = set()  # names bound to the threading module
    fn_aliases = {}      # local name -> Lock/RLock/Condition
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "threading":
                    mod_aliases.add(a.asname or "threading")
        elif isinstance(node, ast.ImportFrom) and \
                node.module == "threading":
            for a in node.names:
                if a.name in ("Lock", "RLock", "Condition"):
                    fn_aliases[a.asname or a.name] = a.name
    pragmas = _Pragmas(source)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        kind = None
        if isinstance(f, ast.Attribute) and \
                f.attr in ("Lock", "RLock", "Condition") and \
                isinstance(f.value, ast.Name) and f.value.id in mod_aliases:
            kind = f.attr
        elif isinstance(f, ast.Name) and f.id in fn_aliases:
            kind = fn_aliases[f.id]
        if kind is None or pragmas.allows(node.lineno, "L1101"):
            continue
        findings.append(Finding(
            "L1101", path, node.lineno,
            f"raw threading.{kind}() — construct locks through "
            f"utils.locks.Ranked{'Condition' if kind == 'Condition' else kind}"
            f"(name) so the deadlock witness sees them; a deliberately "
            f"unranked site carries allow(L1101)"))


_POLICY_LITERAL_FILES = ("mxnet_tpu/kernels/cost_model.py",
                         "mxnet_tpu/analysis/fusion.py")


def _policy_literal_scoped(path, source):
    """Files the decision-point discipline applies to: the fusion
    cost-model pair (where round 24 moved every threshold behind
    ``declare_decision``). Fixtures opt in with a
    ``# graft-lint: scope(policy-literal)`` marker."""
    norm = path.replace(os.sep, "/")
    if norm.endswith(_POLICY_LITERAL_FILES):
        return True
    return "graft-lint: scope(policy-literal)" in source


def _literal_num(node):
    """The numeric value of a pure-literal expression (``8``,
    ``1 << 22``, ``-4``, ``4 * 1024``), or None when any operand is a
    name/call — a named threshold is exactly what the rule wants."""
    if isinstance(node, ast.Constant):
        v = node.value
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return v
        return None
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, (ast.USub, ast.UAdd)):
        v = _literal_num(node.operand)
        if v is None:
            return None
        return -v if isinstance(node.op, ast.USub) else v
    if isinstance(node, ast.BinOp):
        lv, rv = _literal_num(node.left), _literal_num(node.right)
        if lv is None or rv is None:
            return None
        try:
            if isinstance(node.op, ast.LShift):
                return lv << rv
            if isinstance(node.op, ast.Mult):
                return lv * rv
            if isinstance(node.op, ast.Add):
                return lv + rv
            if isinstance(node.op, ast.Sub):
                return lv - rv
            if isinstance(node.op, ast.Pow):
                return lv ** rv
        except (TypeError, ValueError, OverflowError):
            return None
    return None


def _is_declare_decision(node):
    """True for ``declare_decision(...)`` / ``x.declare_decision(...)``
    call values — the sanctioned way a policy constant is born."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (isinstance(f, ast.Name) and f.id == "declare_decision") or \
        (isinstance(f, ast.Attribute) and f.attr == "declare_decision")


def check_policy_literal(path, tree, source, findings):
    """L1201: a performance-policy threshold that bypassed the
    DecisionPoint registry. Two species:

    - a module-level ALL-CAPS constant assigned a numeric-literal
      expression instead of a ``declare_decision(...)`` result;
    - a comparison against an inline numeric literal past the
      structural range (|n| > 8) — a threshold hidden where even a
      constant-name grep cannot find it.
    """
    if not _policy_literal_scoped(path, source):
        return
    pragmas = _Pragmas(source)

    def emit(node, msg):
        if not pragmas.allows(node.lineno, "L1201"):
            findings.append(Finding("L1201", path, node.lineno, msg))

    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and node.value:
            target, value = node.target.id, node.value
        if target is None or not target.isupper() \
                or _is_declare_decision(value):
            continue
        if _literal_num(value) is not None:
            emit(node, f"numeric policy literal bound to {target!r} — "
                 "declare it with autotune.declare_decision(name, "
                 "candidates, default) so measured records can beat "
                 "it; hardware geometry carries allow(L1201)")
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        for comparator in node.comparators:
            v = _literal_num(comparator)
            if v is not None and abs(v) > 8:
                emit(node, f"inline comparison against numeric policy "
                     f"literal {v!r} — route the threshold through a "
                     "declared DecisionPoint (autotune."
                     "declare_decision) and consult autotune.lookup; "
                     "a non-policy constant carries allow(L1201)")


def _guards_comment(source_lines, lineno):
    """The ``# guards: a, b`` attr set for the assignment at 1-based
    ``lineno`` — from the same line's trailing comment or the line
    immediately above."""
    for text in (source_lines[lineno - 1],
                 source_lines[lineno - 2] if lineno >= 2 else ""):
        if "# guards:" in text:
            frag = text.split("# guards:", 1)[1]
            names = {n.strip() for n in frag.split(",")}
            return {n for n in names if n and n.isidentifier()}
    return None


def _ranked_ctor_name(value):
    """'RankedLock'/'RankedRLock'/'RankedCondition' when ``value`` is a
    ranked-factory call (possibly dotted: _locks.RankedLock), else
    None."""
    if not isinstance(value, ast.Call):
        return None
    dn = _dotted(value.func) or ""
    last = dn.split(".")[-1]
    return last if last in _RANKED_CTORS else None


class _LockDecl:
    """One ranked-lock declaration site: the holder expressions that
    count as 'holding it' and the attrs/globals it guards."""

    def __init__(self, expr, guards):
        self.exprs = {expr}   # dotted holder exprs ("self._lock", "_LOCK")
        self.guards = guards or set()


def _collect_lock_decls(tree, source):
    """(class_decls, module_decls, holder_exprs): lock declarations by
    class and at module level, plus every dotted expr that denotes a
    ranked lock in this file (for L1103's with-body scan). Conditions
    built over an existing lock (``RankedCondition(lock=self._lock)``)
    alias that lock's declaration."""
    lines = source.splitlines()
    class_decls = {}   # ClassDef -> {attr_name: _LockDecl}
    module_decls = {}  # global name -> _LockDecl
    holder_exprs = set()

    def scan_assign(node, bucket, expr_of):
        ctor = _ranked_ctor_name(node.value)
        if ctor is None:
            return
        for t in node.targets:
            key = expr_of(t)
            if key is None:
                continue
            guards = _guards_comment(lines, node.lineno)
            # RankedCondition(lock=self._lock) shares the lock's
            # identity: holding the condition IS holding the lock
            shared = None
            for kw in node.value.keywords:
                if kw.arg == "lock":
                    shared = _dotted(kw.value)
            if shared is not None and shared.startswith("self."):
                shared = shared[len("self."):]
            if shared is not None and shared in bucket:
                decl = bucket[shared]
                decl.exprs.add(_holder_expr(key, expr_of))
                if guards:
                    decl.guards |= guards
            else:
                bucket[key] = _LockDecl(_holder_expr(key, expr_of),
                                        guards)

    def _holder_expr(key, expr_of):
        return ("self." + key) if expr_of is _self_attr else key

    def _self_attr(t):
        if isinstance(t, ast.Attribute) and \
                isinstance(t.value, ast.Name) and t.value.id == "self":
            return t.attr
        return None

    def _global_name(t):
        return t.id if isinstance(t, ast.Name) else None

    for node in tree.body:
        if isinstance(node, ast.Assign):
            scan_assign(node, module_decls, _global_name)
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        bucket = class_decls.setdefault(cls, {})
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                scan_assign(node, bucket, _self_attr)
    for bucket in class_decls.values():
        for decl in bucket.values():
            holder_exprs |= decl.exprs
    for decl in module_decls.values():
        holder_exprs |= decl.exprs
    return class_decls, module_decls, holder_exprs


def _with_holds(node, holder_exprs, aliases):
    """Holder exprs this With statement acquires."""
    held = set()
    for item in node.items:
        dn = _dotted(item.context_expr)
        if dn is None:
            continue
        if dn in holder_exprs or dn in aliases:
            held.add(dn)
    return held


def _lock_alias_target(value):
    """'self._lock'-style dotted expr when ``value`` re-binds a lock
    (``lock = self._lock`` / ``lock = getattr(self, "_lock", None)``),
    else None."""
    dn = _dotted(value)
    if dn is not None:
        return dn
    if isinstance(value, ast.Call) and \
            isinstance(value.func, ast.Name) and \
            value.func.id == "getattr" and len(value.args) >= 2 and \
            isinstance(value.args[0], ast.Name) and \
            value.args[0].id == "self" and \
            isinstance(value.args[1], ast.Constant):
        return "self." + str(value.args[1].value)
    return None


def _scan_guarded(fn, decl, access_hits):
    """Walk one function; call ``access_hits(node, held)`` for each
    guarded-attr access with whether a holder lock is lexically held.
    Nested defs/lambdas run later, so they restart unheld (a nested
    ``*_locked`` helper is exempt, like its method-level namesake)."""
    aliases = set()
    acquire_style = False
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            tgt = _lock_alias_target(node.value)
            if tgt is not None and tgt in decl.exprs:
                aliases.add(node.targets[0].id)
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire":
            dn = _dotted(node.func.value)
            if dn in decl.exprs or dn in aliases:
                acquire_style = True

    def walk(node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            if node.name.endswith("_locked"):
                return
            held = False
        elif isinstance(node, ast.Lambda):
            held = False
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            if _with_holds(node, decl.exprs, aliases):
                for item in node.items:
                    walk(item, held)
                for child in node.body:
                    walk(child, True)
                return
        access_hits(node, held or acquire_style)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for stmt in fn.body:
        walk(stmt, False)


def check_guarded_by(path, tree, source, findings):
    """L1102: an attr named in a ``# guards:`` annotation accessed
    without its lock. The annotation is the contract; this check makes
    it machine-checked instead of a comment."""
    if not _ranked_lock_scoped(path, source):
        return
    class_decls, module_decls, _ = _collect_lock_decls(tree, source)
    pragmas = _Pragmas(source)

    def flag(node, attr, lockname):
        if pragmas.allows(node.lineno, "L1102"):
            return
        findings.append(Finding(
            "L1102", path, node.lineno,
            f"'{attr}' is guarded by {lockname} (per its # guards: "
            f"annotation) but accessed without holding it; take the "
            f"lock, use a *_locked helper, or annotate a deliberate "
            f"unlocked read with allow(L1102)"))

    def check_fn(fn, decl, is_method):
        if fn.name == "__init__" or fn.name.endswith("_locked"):
            return
        lockname = sorted(decl.exprs)[0]

        def hits(node, held):
            if held:
                return
            if is_method:
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "self" and \
                        node.attr in decl.guards:
                    flag(node, "self." + node.attr, lockname)
            else:
                if isinstance(node, ast.Name) and node.id in decl.guards:
                    flag(node, node.id, lockname)

        _scan_guarded(fn, decl, hits)

    for cls, bucket in class_decls.items():
        for decl in bucket.values():
            if not decl.guards:
                continue
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    check_fn(fn, decl, True)
    for decl in module_decls.values():
        if not decl.guards:
            continue
        for fn in tree.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                check_fn(fn, decl, False)


def check_blocking_under_lock(path, tree, source, findings):
    """L1103: a blocking call lexically inside a ``with <ranked-lock>``
    body — host sync, sleep, file/HTTP IO, retry machinery. The lock
    convoys every contending thread behind the block."""
    if not _ranked_lock_scoped(path, source):
        return
    _, _, holder_exprs = _collect_lock_decls(tree, source)
    if not holder_exprs:
        return
    pragmas = _Pragmas(source)

    def blocking_reason(node):
        if not isinstance(node, ast.Call):
            return None
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _BLOCKING_ATTRS:
            return f".{f.attr}() host sync"
        dn = _dotted(f) or ""
        last = dn.split(".")[-1]
        if last == "sleep":
            return f"{dn}() sleep"
        if dn == "open":
            return "open() file IO"
        if last == "urlopen":
            return f"{dn}() HTTP"
        if last == "RetryPolicy":
            return "RetryPolicy (backoff sleeps)"
        if isinstance(f, ast.Attribute) and f.attr == "run" and \
                "retry" in (_dotted(f.value) or "").lower():
            return f"{_dotted(f)}() retry loop"
        return None

    def walk(node, held, lockname):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            held, lockname = False, None
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            holds = _with_holds(node, holder_exprs, ())
            if holds:
                held, lockname = True, sorted(holds)[0]
        elif held:
            reason = blocking_reason(node)
            if reason is not None and \
                    not pragmas.allows(node.lineno, "L1103"):
                findings.append(Finding(
                    "L1103", path, node.lineno,
                    f"{reason} inside `with {lockname}:` — hoist the "
                    f"blocking call out of the locked region (or "
                    f"annotate a deliberate site with allow(L1103))"))
        for child in ast.iter_child_nodes(node):
            walk(child, held, lockname)

    for stmt in tree.body:
        walk(stmt, False, None)


# ---------------------------------------------------------------------------

def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_paths(paths, repo_root=None, registry=True):
    repo_root = repo_root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    knobs = load_registered_knobs(repo_root)
    findings = []
    want_registry = False
    for path in iter_py_files(paths):
        try:
            source = open(path).read()
            tree = ast.parse(source, path)
        except (OSError, SyntaxError) as e:
            findings.append(Finding("L000", path, 0, f"unparseable: {e}"))
            continue
        check_env_discipline(path, tree, source, knobs, findings)
        check_jit_safety(path, tree, source, findings)
        check_jit_nocache(path, tree, source, findings)
        check_step_host_sync(path, tree, source, findings)
        check_wallclock_deadlines(path, tree, source, findings)
        check_graph_mutation(path, tree, source, findings)
        check_raw_sharding_construction(path, tree, source, findings)
        check_raw_pallas_import(path, tree, source, findings)
        check_raw_counter_mutation(path, tree, source, findings)
        check_salt_assembly(path, tree, source, findings)
        check_swallowed_exceptions(path, tree, source, findings)
        check_op_docstrings(path, tree, source, findings)
        check_raw_lock_construction(path, tree, source, findings)
        check_guarded_by(path, tree, source, findings)
        check_blocking_under_lock(path, tree, source, findings)
        check_policy_literal(path, tree, source, findings)
        if os.path.basename(path) == "registry.py":
            want_registry = True
    if registry and want_registry:
        try:
            registry_checks(findings)
        except Exception as e:  # package not importable here: AST-only
            findings.append(Finding(
                "R000", "mxnet_tpu", 0,
                f"registry checks skipped (import failed: {e})"))
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="graft_lint", description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: mxnet_tpu)")
    ap.add_argument("--no-registry", action="store_true",
                    help="skip the import-based registry checks")
    args = ap.parse_args(argv)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    paths = args.paths or [os.path.join(repo_root, "mxnet_tpu")]
    findings = lint_paths(paths, repo_root=repo_root,
                          registry=not args.no_registry)
    for f in findings:
        print(f)
    print(f"graft_lint: {len(findings)} finding(s) in "
          f"{len(list(iter_py_files(paths)))} file(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
