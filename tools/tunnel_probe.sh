#!/bin/bash
# Background TPU-tunnel health probe. One probe process at a time, spaced
# widely (25 min) so a wedged tunnel isn't hammered. Logs to
# /tmp/tunnel_probe.log. A healthy tunnel answers jax.devices() in <60s.
LOG=/tmp/tunnel_probe.log
while true; do
  ts=$(date -u +%FT%TZ)
  raw=$(timeout -k 10 150 python -c "import jax; print(jax.devices())" 2>&1)
  rc=$?
  out=$(printf '%s\n' "$raw" | tail -1)
  if [ $rc -eq 0 ] && echo "$out" | grep -q "TpuDevice\|axon"; then
    echo "$ts HEALTHY $out" >> "$LOG"
    # pounce: run the round's on-chip agenda while the window is open
    # (idempotent + locked; see tools/tpu_agenda.sh)
    "$(dirname "$0")/tpu_agenda.sh"
  else
    echo "$ts down rc=$rc $out" >> "$LOG"
  fi
  sleep 1500
done
