#!/bin/bash
# The round-5 on-chip measurement agenda, run back-to-back in one healthy
# tunnel window (BENCH_NOTES_r05.md "ready-to-run" list). Writes artifacts
# into the repo root and logs to /tmp/tpu_agenda.log. Idempotent: skips
# steps whose artifact already exists; a lock prevents concurrent runs.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
LOG=/tmp/tpu_agenda.log
LOCK=/tmp/tpu_agenda.lock
cd "$REPO"

exec 9>"$LOCK"
if ! flock -n 9; then
  echo "$(date -u +%FT%TZ) agenda already running" >> "$LOG"
  exit 0
fi

log() { echo "$(date -u +%FT%TZ) $*" >> "$LOG"; }

run_step() {  # name, artifact, timeout_s, cmd...
  local name="$1" artifact="$2" tmo="$3"; shift 3
  if [ -s "$artifact" ] && ! grep -q "cpu_fallback\|unavailable" "$artifact"; then
    log "$name: artifact exists, skipping"
    return 0
  fi
  log "$name: starting ($*)"
  local out
  out=$(timeout -k 30 "$tmo" "$@" 2>>"$LOG")
  local rc=$?
  # keep the LAST json line as the artifact
  local line
  line=$(printf '%s\n' "$out" | grep '^{' | tail -1)
  if [ -n "$line" ]; then
    printf '%s\n' "$line" > "$artifact"
    log "$name: OK -> $artifact"
  else
    log "$name: rc=$rc, no json line"
  fi
  return $rc
}

log "=== agenda start ==="

# 1. the headline bench (phase-aware supervisor handles retries itself)
run_step bench BENCH_LOCAL_r05.json 3600 python bench.py

# 2. no-framework ceiling for the same model
run_step rawjax RAWJAX_r05.json 2400 env BENCH_CHILD= BENCH_MODE=rawjax \
  python bench.py

# 3. XPlane profile of the bf16 b512 step + inline top-self-time table
run_step profile PROFILE_r05.json 2400 env BENCH_MODE=profile \
  BENCH_BATCH=512 BENCH_PROFILE_DIR=bench_profile_r05 python bench.py

# 4. data-FED training rate vs synthetic ceiling (decode+H2D overlap)
run_step overlap OVERLAP_r05.json 2400 python \
  examples/train_imagenet_rec.py --bf16 --depth 50 --image-size 224 \
  --batch 256 --images 2048 --steps 8 --overlap-report

log "=== agenda end ==="
